"""Device kernel tests: hashing, sort, merge join (reference test layer 3 —
kernel tests on single-device arrays)."""

import numpy as np
import pyarrow as pa
import pytest

from hyperspace_tpu.io import columnar
from hyperspace_tpu.ops import hash_partition, join, sort


def batch_of(**cols):
    return columnar.from_arrow(pa.table(cols))


def test_bucket_ids_deterministic_and_in_range():
    b = batch_of(k=np.arange(1000, dtype=np.int64))
    ids1 = np.asarray(hash_partition.bucket_ids(b, ["k"], 8))
    ids2 = np.asarray(hash_partition.bucket_ids(b, ["k"], 8))
    assert (ids1 == ids2).all()
    assert ids1.min() >= 0 and ids1.max() < 8
    # reasonable balance: no empty bucket at n=1000, B=8
    assert len(np.unique(ids1)) == 8


def test_bucket_ids_value_stability_across_batches():
    """Same key value must land in the same bucket regardless of batch
    composition — required for co-bucketed joins."""
    b1 = batch_of(k=np.array([5, 100, 7], dtype=np.int64))
    b2 = batch_of(k=np.array([100, 9999], dtype=np.int64))
    ids1 = np.asarray(hash_partition.bucket_ids(b1, ["k"], 16))
    ids2 = np.asarray(hash_partition.bucket_ids(b2, ["k"], 16))
    assert ids1[1] == ids2[0]


def test_string_bucket_stability():
    b1 = batch_of(s=pa.array(["apple", "pear"]))
    b2 = batch_of(s=pa.array(["zebra", "pear", "kiwi"]))
    ids1 = np.asarray(hash_partition.bucket_ids(b1, ["s"], 32))
    ids2 = np.asarray(hash_partition.bucket_ids(b2, ["s"], 32))
    assert ids1[1] == ids2[1]


def test_multicolumn_hash_differs_by_order():
    b = batch_of(a=np.array([1, 2], dtype=np.int64),
                 c=np.array([2, 1], dtype=np.int64))
    h_ac = np.asarray(hash_partition.batch_hash32(b, ["a", "c"]))
    h_ca = np.asarray(hash_partition.batch_hash32(b, ["c", "a"]))
    assert not (h_ac == h_ca).all()


def test_sort_lexicographic_multi_key():
    b = batch_of(a=np.array([2, 1, 2, 1], dtype=np.int64),
                 c=np.array([0.1, 0.9, 0.0, 0.5]))
    out = columnar.to_arrow(sort.sort_batch(b, ["a", "c"]))
    assert out.column("a").to_pylist() == [1, 1, 2, 2]
    assert out.column("c").to_pylist() == [0.5, 0.9, 0.0, 0.1]


def test_sort_strings():
    b = batch_of(s=pa.array(["pear", "apple", "kiwi"]),
                 v=np.array([1, 2, 3], dtype=np.int64))
    out = columnar.to_arrow(sort.sort_batch(b, ["s"]))
    assert out.column("s").to_pylist() == ["apple", "kiwi", "pear"]
    assert out.column("v").to_pylist() == [2, 3, 1]


def test_sort_nulls_first():
    b = columnar.from_arrow(pa.table({"x": pa.array([3, None, 1], type=pa.int64())}))
    out = columnar.to_arrow(sort.sort_batch(b, ["x"]))
    assert out.column("x").to_pylist() == [None, 1, 3]


def test_bucket_boundaries():
    import jax.numpy as jnp
    sorted_ids = jnp.asarray(np.array([0, 0, 2, 2, 2, 3], dtype=np.int32))
    starts, ends = sort.bucket_boundaries(sorted_ids, 4)
    assert list(np.asarray(starts)) == [0, 2, 2, 5]
    assert list(np.asarray(ends)) == [2, 2, 5, 6]


def test_merge_join_indices_duplicates():
    import jax.numpy as jnp
    left = jnp.asarray(np.array([1, 1, 2, 5], dtype=np.int32))
    right = jnp.asarray(np.array([1, 2, 2, 7], dtype=np.int32))
    li, ri = join.merge_join_indices(left, right)
    pairs = sorted(zip(np.asarray(li).tolist(), np.asarray(ri).tolist()))
    assert pairs == [(0, 0), (1, 0), (2, 1), (2, 2)]


def test_merge_join_no_matches():
    import jax.numpy as jnp
    li, ri = join.merge_join_indices(jnp.asarray(np.array([1, 2], np.int32)),
                                     jnp.asarray(np.array([3, 4], np.int32)))
    assert len(np.asarray(li)) == 0


def test_sort_merge_join_matches_numpy(sample_parquet):
    rng = np.random.default_rng(7)
    lk = rng.integers(0, 20, 200).astype(np.int64)
    rk = rng.integers(0, 20, 80).astype(np.int64)
    left = batch_of(k=lk, v1=np.arange(200, dtype=np.int64))
    right = batch_of(k=rk, v2=np.arange(80, dtype=np.int64))
    out = columnar.to_arrow(join.sort_merge_join(left, right, ["k"], ["k"]))
    df = out.to_pandas()
    import pandas as pd
    ref = pd.DataFrame({"k": lk, "v1": np.arange(200)}).merge(
        pd.DataFrame({"k": rk, "v2": np.arange(80)}), on="k")
    cols = ["k", "v1", "v2"]
    a = df[cols].sort_values(cols).reset_index(drop=True)
    b_ = ref[cols].sort_values(cols).reset_index(drop=True)
    assert len(a) == len(b_)
    assert (a.to_numpy() == b_.to_numpy()).all()


def test_sort_merge_join_string_keys_cross_dictionary():
    left = batch_of(s=pa.array(["a", "m", "z"]), x=np.array([1, 2, 3], np.int64))
    right = batch_of(s=pa.array(["m", "q"]), y=np.array([10, 20], np.int64))
    out = columnar.to_arrow(join.sort_merge_join(left, right, ["s"], ["s"]))
    assert out.column("s").to_pylist() == ["m"]
    assert out.column("x").to_pylist() == [2]
    assert out.column("y").to_pylist() == [10]


def test_join_duplicate_output_names_get_suffix():
    left = batch_of(k=np.array([1], np.int64), v=np.array([1], np.int64))
    right = batch_of(k=np.array([1], np.int64), v=np.array([9], np.int64))
    out = columnar.to_arrow(join.sort_merge_join(left, right, ["k"], ["k"]))
    assert out.column_names == ["k", "v", "k_r", "v_r"]


def test_join_null_keys_match_nothing():
    """SQL semantics: NULL join keys never match — not even each other, and
    never the null sentinel payload (0 / empty string)."""
    left = columnar.from_arrow(pa.table({
        "k": pa.array([None, -5, 3, 0], type=pa.int64()),
        "x": pa.array([1, 2, 3, 4], type=pa.int64())}))
    right = columnar.from_arrow(pa.table({
        "k": pa.array([0, None, -5], type=pa.int64()),
        "y": pa.array([10, 20, 30], type=pa.int64())}))
    out = columnar.to_arrow(join.sort_merge_join(left, right, ["k"], ["k"]))
    pairs = sorted(zip(out.column("x").to_pylist(), out.column("y").to_pylist()))
    assert pairs == [(2, 30), (4, 10)]


def test_join_null_string_keys():
    left = batch_of(s=pa.array(["a", None, ""]), x=np.array([1, 2, 3], np.int64))
    right = batch_of(s=pa.array([None, "", "a"]), y=np.array([10, 20, 30], np.int64))
    out = columnar.to_arrow(join.sort_merge_join(left, right, ["s"], ["s"]))
    pairs = sorted(zip(out.column("x").to_pylist(), out.column("y").to_pylist()))
    assert pairs == [(1, 30), (3, 20)]


def test_bucketed_join_empty_side():
    """An empty side must yield an empty join, not a crash."""
    from hyperspace_tpu.ops.bucketed_join import bucketed_sort_merge_join
    import pyarrow as _pa
    left = columnar.from_arrow(_pa.table({
        "k": _pa.array([], type=_pa.int64()),
        "x": _pa.array([], type=_pa.int64())}))
    right = batch_of(k=np.array([1, 2], np.int64), y=np.array([5, 6], np.int64))
    out = bucketed_sort_merge_join(left, right, np.zeros(4, np.int64),
                                   np.array([1, 1, 0, 0], np.int64),
                                   ["k"], ["k"])
    assert out.num_rows == 0
    assert columnar.to_arrow(out).column_names == ["k", "x", "k_r", "y"]


def test_bucketed_left_outer_unmatched_rows_get_null():
    """Regression: unmatched left rows must emit right index -1, not an
    arbitrary right row (the outer fill used to overwrite the true match
    counts before _expand_core derived its matched mask)."""
    from hyperspace_tpu.ops.bucketed_join import bucketed_join_indices
    left = batch_of(k=np.array([1, 2, 3], np.int64),
                    x=np.array([10, 20, 30], np.int64))
    right = batch_of(k=np.array([1, 3], np.int64),
                     y=np.array([100, 300], np.int64))
    li, ri = bucketed_join_indices(left, right, np.array([3], np.int64),
                                   np.array([2], np.int64), ["k"], ["k"],
                                   how="left_outer")
    pairs = sorted(zip(np.asarray(li).tolist(), np.asarray(ri).tolist()))
    assert pairs == [(0, 0), (1, -1), (2, 1)]


def test_bucketed_outer_join_null_payloads():
    """Full outer-join assembly: unmatched rows carry nulls on the other
    side, for both left_outer and right_outer, across buckets."""
    from hyperspace_tpu.ops.bucketed_join import bucketed_sort_merge_join
    left = batch_of(k=np.array([1, 2, 5, 6], np.int64),
                    x=np.array([10, 20, 50, 60], np.int64))
    right = batch_of(k=np.array([2, 5, 7], np.int64),
                     y=np.array([200, 500, 700], np.int64))
    # Two buckets: left has rows [1,2] then [5,6]; right [2] then [5,7].
    out = columnar.to_arrow(bucketed_sort_merge_join(
        left, right, np.array([2, 2], np.int64), np.array([1, 2], np.int64),
        ["k"], ["k"], how="left_outer"))
    rows = sorted(zip(out.column("x").to_pylist(), out.column("y").to_pylist()))
    assert rows == [(10, None), (20, 200), (50, 500), (60, None)]

    out = columnar.to_arrow(bucketed_sort_merge_join(
        left, right, np.array([2, 2], np.int64), np.array([1, 2], np.int64),
        ["k"], ["k"], how="right_outer"))
    rows = sorted(zip(out.column("x").to_pylist(), out.column("y").to_pylist()),
                  key=lambda t: (t[0] is None, t))
    assert rows == [(20, 200), (50, 500), (None, 700)]


def test_bucketed_left_outer_null_keys_unmatched():
    """NULL join keys never match but still appear once in a left outer."""
    from hyperspace_tpu.ops.bucketed_join import bucketed_sort_merge_join
    left = columnar.from_arrow(pa.table({
        "k": pa.array([1, None, 3], type=pa.int64()),
        "x": pa.array([10, 20, 30], type=pa.int64())}))
    right = batch_of(k=np.array([1, 3], np.int64),
                     y=np.array([100, 300], np.int64))
    out = columnar.to_arrow(bucketed_sort_merge_join(
        left, right, np.array([3], np.int64), np.array([2], np.int64),
        ["k"], ["k"], how="left_outer"))
    rows = sorted(zip(out.column("x").to_pylist(), out.column("y").to_pylist()))
    assert rows == [(10, 100), (20, None), (30, 300)]


def test_narrow_key_transport_matches_wide_path(tmp_path):
    """`_stage_key_tree`'s lo32 narrow transport must produce the exact
    same bucket layout and row order as the wide int64 path — bucket ids
    ride the same [hi=0, lo] hash lane chain."""
    import os
    import pyarrow.parquet as pq
    from hyperspace_tpu.io.builder import write_bucketed_table

    rng = np.random.default_rng(11)
    n = 5000
    table = pa.table({
        "k": rng.integers(0, 1 << 31, n).astype(np.int64),  # fits uint32
        "v": np.arange(n, dtype=np.int64),
    })
    narrow_dir = str(tmp_path / "narrow")
    wide_dir = str(tmp_path / "wide")
    write_bucketed_table(table, ["k"], 8, narrow_dir)  # narrow staging
    write_bucketed_table(table, ["k"], 8, wide_dir,
                         key_batch=columnar.from_arrow(table))  # wide lanes
    narrow_files = sorted(os.listdir(narrow_dir))
    assert narrow_files == sorted(os.listdir(wide_dir))
    for f in narrow_files:
        a = pq.read_table(os.path.join(narrow_dir, f))
        b = pq.read_table(os.path.join(wide_dir, f))
        assert a.equals(b), f

    # Values outside uint32 range must take the wide path and still work.
    big = pa.table({
        "k": (rng.integers(0, 1 << 31, 1000).astype(np.int64)
              - (1 << 30)) * 8,  # negatives + >2^32
        "v": np.arange(1000, dtype=np.int64),
    })
    big_dir = str(tmp_path / "big")
    write_bucketed_table(big, ["k"], 4, big_dir)
    rows = sum(pq.read_table(os.path.join(big_dir, f)).num_rows
               for f in os.listdir(big_dir) if f.endswith(".parquet"))
    assert rows == 1000
    for f in os.listdir(big_dir):
        if f.endswith(".parquet"):
            ks = pq.read_table(os.path.join(big_dir, f)).column("k").to_pylist()
            assert ks == sorted(ks)


def test_float_hash_identity_shared_between_paths():
    """Eager column_hash32 and the jitted build core must agree on float
    keys — on-disk bucket layout depends on one shared hash identity."""
    from hyperspace_tpu.ops.build import _tree_hash_lanes
    from hyperspace_tpu.ops.hash_partition import flat_hash32
    from hyperspace_tpu.io.columnar import batch_to_tree
    b = batch_of(f=np.array([-1.5, 0.0, 2.25, 1e300], dtype=np.float64))
    eager = np.asarray(hash_partition.column_hash32(b.column("f")))
    tree, _ = batch_to_tree(b)
    jitted = np.asarray(flat_hash32(_tree_hash_lanes(tree["f"])))
    assert (eager == jitted).all()


def _bucket_order(batch, keys, num_buckets):
    """Lay a batch out concat-in-bucket-order with per-bucket lengths."""
    import jax.numpy as jnp
    ids = np.asarray(hash_partition.bucket_ids(batch, keys, num_buckets))
    order = np.argsort(ids, kind="stable").astype(np.int32)
    lengths = np.bincount(ids, minlength=num_buckets).astype(np.int64)
    return batch.take(jnp.asarray(order)), lengths


def test_bucketed_join_hot_key_skew_falls_back_and_matches():
    """One key owning 50% of rows must not inflate the padded layout to
    O(B * rows): the skew guard routes to the global merge join, and the
    result multiset is unchanged (VERDICT r1 weak #3)."""
    from hyperspace_tpu.ops import bucketed_join as bj

    num_buckets = 64
    n = 100_000
    rng = np.random.default_rng(7)
    hot = np.full(n // 2, 42, dtype=np.int64)
    cold = rng.integers(1000, 1000 + n, n // 2).astype(np.int64)
    lkeys = np.concatenate([hot, cold])
    left = batch_of(k=lkeys, x=np.arange(n, dtype=np.int64))
    # Right: hot key appears 3x, plus a slice of the cold keys once each.
    rkeys = np.concatenate([np.full(3, 42, np.int64), cold[:1000]])
    right = batch_of(k=rkeys, y=np.arange(len(rkeys), dtype=np.int64))

    lb, ll = _bucket_order(left, ["k"], num_buckets)
    rb, rl = _bucket_order(right, ["k"], num_buckets)

    li, ri = bj.bucketed_join_indices(lb, rb, ll, rl, ["k"], ["k"])
    got_l = np.asarray(lb.column("k").data)[np.asarray(li)]
    got_r = np.asarray(rb.column("k").data)[np.asarray(ri)]
    assert (got_l == got_r).all()
    # Expected inner-join multiset: hot key 50000*3 plus 1000 cold matches
    # (cold keys are drawn with replacement -> count actual matches).
    r_counts = {}
    for k in rkeys:
        r_counts[k] = r_counts.get(k, 0) + 1
    expected_total = sum(r_counts.get(k, 0) for k in lkeys)
    assert len(np.asarray(li)) == expected_total
    # Spot-check multiset equality on the cold slice.
    got_cold = np.sort(got_l[got_l != 42])
    exp_cold = np.sort(np.concatenate(
        [np.repeat(k, r_counts.get(k, 0)) for k in cold if k in r_counts]))
    assert (got_cold == exp_cold).all()


def test_bucketed_join_skew_left_outer_matches_global():
    """Left-outer under skew: unmatched left rows emit -1 exactly once."""
    from hyperspace_tpu.ops import bucketed_join as bj

    num_buckets = 64
    n = 80_000
    lkeys = np.concatenate([np.full(n // 2, 7, np.int64),
                            np.arange(10_000, 10_000 + n // 2, dtype=np.int64)])
    left = batch_of(k=lkeys)
    right = batch_of(k=np.array([7, 10_000, 10_001], np.int64))
    lb, ll = _bucket_order(left, ["k"], num_buckets)
    rb, rl = _bucket_order(right, ["k"], num_buckets)

    li, ri = bj.bucketed_join_indices(lb, rb, ll, rl, ["k"], ["k"],
                                      how="left_outer")
    li, ri = np.asarray(li), np.asarray(ri)
    # Every left row appears exactly once (each matches <= 1 right row).
    assert len(li) == n
    assert sorted(li.tolist()) == list(range(n))
    lk = np.asarray(lb.column("k").data)
    matched = np.isin(lk[li], [7, 10_000, 10_001])
    assert ((ri >= 0) == matched).all()


def test_host_bucket_ids_match_device():
    """The host (numpy) hash mirror must agree with THE device hash
    identity for every key dtype — bucket pruning and the on-disk layout
    depend on it."""
    from hyperspace_tpu.ops.host_hash import host_bucket_ids

    rng = np.random.default_rng(13)
    n, B = 257, 32
    cases = {
        "int64": rng.integers(-2**62, 2**62, n).astype(np.int64),
        "int32": rng.integers(-2**31, 2**31 - 1, n).astype(np.int32),
        "int16": rng.integers(-2**15, 2**15 - 1, n).astype(np.int16),
        "bool": rng.integers(0, 2, n).astype(bool),
        "float64": rng.standard_normal(n) * 1e6,
        "float32": (rng.standard_normal(n) * 1e3).astype(np.float32),
        "string": np.array(["v_%d" % v for v in rng.integers(0, 50, n)]),
    }
    for dtype, vals in cases.items():
        table = pa.table({"k": pa.array(vals)})
        batch = columnar.from_arrow(table)
        dev = np.asarray(hash_partition.bucket_ids(batch, ["k"], B))
        host = host_bucket_ids([vals], [dtype], B)
        assert (dev == host).all(), f"identity mismatch for {dtype}"
    # Multi-column combine order matters: (int64, string) pair.
    table = pa.table({"a": pa.array(cases["int64"]),
                      "s": pa.array(cases["string"])})
    batch = columnar.from_arrow(table)
    dev = np.asarray(hash_partition.bucket_ids(batch, ["a", "s"], B))
    host = host_bucket_ids([cases["int64"], cases["string"]],
                           ["int64", "string"], B)
    assert (dev == host).all()


def test_stddev_aggregate_and_host_device_parity():
    """stddev (sample) on both lanes; host-lane aggregation must agree
    with the device lane bit-for-bit on grouping and SQL null semantics."""
    from hyperspace_tpu.io.columnar import from_arrow
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec
    from hyperspace_tpu.plan.schema import Schema

    rng = np.random.default_rng(3)
    n = 4000
    table = pa.table({
        "g": rng.integers(0, 37, n).astype(np.int64),
        "x": pa.array([None if i % 11 == 0 else float(v) for i, v in
                       enumerate(rng.standard_normal(n))], type=pa.float64()),
        "y": rng.integers(-100, 100, n).astype(np.int64),
    })
    schema = Schema.from_arrow(table.schema)
    specs = [AggSpec("count", "*", "cnt"), AggSpec("count", "x", "cx"),
             AggSpec("sum", "y", "sy"), AggSpec("avg", "x", "ax"),
             AggSpec("min", "y", "mny"), AggSpec("max", "y", "mxy"),
             AggSpec("stddev", "x", "sx")]
    from hyperspace_tpu.plan.nodes import Scan
    out_schema = Aggregate(["g"], specs,
                           Scan(["/nonexistent"], schema)).schema

    host = group_aggregate(from_arrow(table, device=False), ["g"], specs,
                           out_schema)
    dev = group_aggregate(from_arrow(table, device=True), ["g"], specs,
                          out_schema)
    import pandas as pd
    from hyperspace_tpu.io.columnar import to_arrow
    h = to_arrow(host).to_pandas().sort_values("g").reset_index(drop=True)
    d = to_arrow(dev).to_pandas().sort_values("g").reset_index(drop=True)
    pd.testing.assert_frame_equal(h, d, check_exact=False, rtol=1e-9)
    # Cross-check stddev against pandas (sample stddev).
    ref = (table.to_pandas().groupby("g")["x"].std()
           .reset_index(drop=True))
    assert np.allclose(h["sx"].to_numpy(), ref.to_numpy(),
                       rtol=1e-9, equal_nan=True)


def test_stddev_no_catastrophic_cancellation():
    """stddev over large-offset values (timestamp magnitude) must not
    cancel: two-pass shifted variance on both lanes."""
    from hyperspace_tpu.io.columnar import from_arrow
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    rng = np.random.default_rng(1)
    x = 1.7e15 + rng.standard_normal(1000)
    table = pa.table({"g": np.zeros(1000, np.int64), "x": x})
    schema = Schema.from_arrow(table.schema)
    specs = [AggSpec("stddev", "x", "sx")]
    out_schema = Aggregate(["g"], specs, Scan(["/nx"], schema)).schema
    expected = np.std(x, ddof=1)
    for device in (False, True):
        out = group_aggregate(from_arrow(table, device=device), ["g"],
                              specs, out_schema)
        got = float(np.asarray(out.column("sx").data)[0])
        assert abs(got - expected) < 1e-3, f"device={device}: {got}"


def test_host_join_rejects_mismatched_key_lists():
    """The host lane must enforce the same key-list validation as the
    device path instead of silently truncating via zip."""
    from hyperspace_tpu.io.columnar import from_arrow
    from hyperspace_tpu.ops.join import sort_merge_join
    from hyperspace_tpu.exceptions import HyperspaceException

    left = from_arrow(pa.table({"a": np.arange(3, dtype=np.int64),
                                "b": np.arange(3, dtype=np.int64)}),
                      device=False)
    right = from_arrow(pa.table({"a": np.arange(3, dtype=np.int64)}),
                       device=False)
    with pytest.raises(HyperspaceException):
        sort_merge_join(left, right, ["a", "b"], ["a"])


def test_host_join_empty_sides():
    """Empty build side on the host lane: outer joins emit -1, inner joins
    emit nothing — no IndexError from indexing an empty order array."""
    from hyperspace_tpu.io.columnar import from_arrow
    from hyperspace_tpu.ops.join import host_join_indices

    left = from_arrow(pa.table({"k": np.arange(3, dtype=np.int64)}),
                      device=False)
    right = from_arrow(pa.table({"k": pa.array([], type=pa.int64())}),
                       device=False)
    li, ri = host_join_indices(left, right, ["k"], ["k"], how="left_outer")
    assert li.tolist() == [0, 1, 2] and ri.tolist() == [-1, -1, -1]
    li, ri = host_join_indices(left, right, ["k"], ["k"], how="inner")
    assert len(li) == 0 and len(ri) == 0
    li, ri = host_join_indices(right, left, ["k"], ["k"], how="inner")
    assert len(li) == 0


def test_float_key_negative_zero_and_nan_uniform_across_lanes():
    """-0.0 joins 0.0 and NaN joins NaN identically on every path: the
    host packed fast path (raw float compare), the host lane-encoded
    path, and the device encode (normalized order bits) — the advisor's
    round-2 medium finding."""
    lk = np.array([-0.0, 0.0, np.nan, 1.5])
    rk = np.array([0.0, np.nan, 1.5, 2.0])
    left = batch_of(k=lk, a=np.arange(4))
    right = batch_of(k=rk, b=np.arange(4))

    # Host packed path (single numeric null-free key).
    li, ri = join.host_join_indices(left, right, ["k"], ["k"])
    packed_pairs = sorted(zip(li.tolist(), ri.tolist()))
    # -0.0 matches 0.0 (rows 0,1 -> right 0); NaN matches NaN (2 -> 1);
    # 1.5 -> 2.
    assert packed_pairs == [(0, 0), (1, 0), (2, 1), (3, 2)]

    # Host lane-encoded path (forced by adding a second key).
    left2 = batch_of(k=lk, k2=pa.array(["x"] * 4), a=np.arange(4))
    right2 = batch_of(k=rk, k2=pa.array(["x"] * 4), b=np.arange(4))
    li2, ri2 = join.host_join_indices(left2, right2, ["k", "k2"],
                                      ["k", "k2"])
    assert sorted(zip(li2.tolist(), ri2.tolist())) == packed_pairs

    # Device encode: group ids of -0.0/0.0 equal; NaNs equal across sides.
    dl = columnar.from_arrow(pa.table({"k": lk}))
    dr = columnar.from_arrow(pa.table({"k": rk}))
    out = join.sort_merge_join(dl, dr, ["k"], ["k"])
    assert out.num_rows == 4

    # Bucket hash identity: -0.0 and 0.0 land in the same bucket on the
    # host mirror (device parity is pinned by
    # test_host_bucket_ids_match_device).
    from hyperspace_tpu.ops.host_hash import host_bucket_ids
    ids = host_bucket_ids([np.array([-0.0, 0.0, np.nan, np.nan])],
                          ["float64"], 16)
    assert ids[0] == ids[1] and ids[2] == ids[3]


def test_float_group_by_negative_zero_one_group():
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    table = pa.table({"k": np.array([-0.0, 0.0, -0.0]),
                      "v": np.array([1, 2, 3], dtype=np.int64)})
    batch = columnar.from_arrow(table)
    schema = Schema.from_arrow(table.schema)
    out_schema = Aggregate(["k"], [AggSpec("sum", "v", "sv")],
                           Scan(["/nx"], schema)).schema
    out = group_aggregate(batch, ["k"], [AggSpec("sum", "v", "sv")],
                          out_schema)
    assert out.num_rows == 1
    assert int(np.asarray(out.column("sv").data)[0]) == 6


def test_staged_sort_permutation_matches_wide_sort():
    """Wide key sets (> MAX_SORT_OPERANDS) sort via staged LSD passes;
    the permutation must equal the single wide lexicographic sort (XLA's
    wide variadic comparator is the q64 compile-time explosion the
    staging exists to avoid)."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.ops.keys import (MAX_SORT_OPERANDS,
                                         staged_sort_permutation)

    rng = np.random.default_rng(5)
    n = 5000
    k = MAX_SORT_OPERANDS * 2 + 3  # forces three chunked passes
    operands = [jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
                for _ in range(k)]
    got = staged_sort_permutation(operands)
    iota = jnp.arange(n, dtype=jnp.int32)
    want = jax.lax.sort([*operands, iota], num_keys=k,
                        is_stable=True)[-1]
    assert (np.asarray(got) == np.asarray(want)).all()
    # narrow path identity too
    got2 = staged_sort_permutation(operands[:3])
    want2 = jax.lax.sort([*operands[:3], iota], num_keys=3,
                         is_stable=True)[-1]
    assert (np.asarray(got2) == np.asarray(want2)).all()


def test_topk_matches_full_sort():
    """topk_batch == sort_batch[:n] on both lanes, across ties, nulls,
    descending keys, and low-cardinality prefixes (candidate blow-up)."""
    import numpy as np

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops.sort import sort_batch, topk_batch

    rng = np.random.default_rng(5)
    n = 50_000
    import pyarrow as pa
    mask = rng.random(n) < 0.05
    table = pa.table({
        "a": pa.array(rng.integers(0, 40, n).astype(np.int64)),  # heavy ties
        "b": pa.array(rng.integers(-1000, 1000, n).astype(np.int64),
                      mask=mask),
        "c": pa.array(rng.random(n)),
        "s": pa.array(np.array(["x", "y", "zz", "w"])[
            rng.integers(0, 4, n)]),
    })
    for device in (False, True):
        batch = columnar.from_arrow(table, device=device)
        for keys in (["a", "b", "s"], ["-a", "c"], ["s", "-b"]):
            want = sort_batch(batch, keys)
            for k in (1, 100, 4096):
                got = topk_batch(batch, keys, k)
                import pandas as pd
                w = columnar.to_arrow(want).to_pandas().head(k) \
                    .reset_index(drop=True)
                g = columnar.to_arrow(got).to_pandas() \
                    .reset_index(drop=True)
                pd.testing.assert_frame_equal(g, w, check_dtype=False)


def test_topk_residency_contract():
    """The documented topk_batch residency contract (`ops/sort.py`):
    host input -> host output; device input -> HOST output on the
    threshold path, DEVICE output on the candidate-cap fallback (the
    low-cardinality prefix where the threshold stops pruning)."""
    import numpy as np
    import pyarrow as pa

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops import sort as sort_mod
    from hyperspace_tpu.ops.sort import topk_batch

    rng = np.random.default_rng(9)
    n = 20_000
    table = pa.table({
        "a": rng.integers(0, 1_000_000, n).astype(np.int64),
        "v": rng.random(n),
    })
    host_batch = columnar.from_arrow(table, device=False)
    assert topk_batch(host_batch, ["a"], 10).is_host

    dev_batch = columnar.from_arrow(table, device=True)
    # Selective prefix: threshold path -> host-resident result.
    out = topk_batch(dev_batch, ["a"], 10)
    assert out.num_rows == 10 and out.is_host
    # Candidate blow-up (constant prefix, cap forced tiny): the full
    # device sort serves the query -> device-resident result.
    const = pa.table({
        "a": np.zeros(n, dtype=np.int64),
        "v": rng.random(n),
    })
    dev_const = columnar.from_arrow(const, device=True)
    old_cap = sort_mod.TOPK_CANDIDATE_CAP
    sort_mod.TOPK_CANDIDATE_CAP = 64
    try:
        out2 = topk_batch(dev_const, ["a", "v"], 10)
    finally:
        sort_mod.TOPK_CANDIDATE_CAP = old_cap
    assert out2.num_rows == 10 and not out2.is_host


def test_hashed_group_phase_matches_exact():
    """Wide (>=5-lane) groupings route through the u64 hash-lane sort;
    aggregation results must be identical to the exact full-lane sort
    path (same groups, same reductions — order may differ)."""
    import numpy as np
    import pyarrow as pa

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops import aggregate as agg_mod
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.plan.nodes import AggSpec
    from hyperspace_tpu.plan.schema import Field, Schema

    rng = np.random.default_rng(12)
    n = 30_000
    table = pa.table({
        "a": rng.integers(0, 8, n).astype(np.int64),
        "b": rng.integers(0, 7, n).astype(np.int64),
        "c": rng.integers(-5, 5, n).astype(np.int64),
        "v": rng.random(n),
    })
    batch = columnar.from_arrow(table, device=True)
    specs = [AggSpec("sum", "v", "s"), AggSpec("count", "*", "n")]
    out_schema = Schema([Field("a", "int64", True), Field("b", "int64", True),
                         Field("c", "int64", True), Field("s", "float64", True),
                         Field("n", "int64", True)])
    # 3 int64 group columns -> 6 lanes >= HASH_GROUP_MIN_LANES
    assert 6 >= agg_mod.HASH_GROUP_MIN_LANES
    got = columnar.to_arrow(group_aggregate(
        batch, ["a", "b", "c"], specs, out_schema)).to_pandas()
    # exact path for reference
    old = agg_mod.HASH_GROUP_MIN_LANES
    agg_mod.HASH_GROUP_MIN_LANES = 10**9
    try:
        want = columnar.to_arrow(group_aggregate(
            batch, ["a", "b", "c"], specs, out_schema)).to_pandas()
    finally:
        agg_mod.HASH_GROUP_MIN_LANES = old
    import pandas as pd
    key = ["a", "b", "c"]
    pd.testing.assert_frame_equal(
        got.sort_values(key).reset_index(drop=True),
        want.sort_values(key).reset_index(drop=True), check_dtype=False)


def test_hashed_group_phase_collision_fallback():
    """A colliding hash must trigger the exact-sort re-run, not a wrong
    answer: force collisions by stubbing the packed flag via a degenerate
    hash (monkeypatch _fmix32 to a constant)."""
    import numpy as np
    import pyarrow as pa

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops import aggregate as agg_mod
    from hyperspace_tpu.ops import hash_partition as hp
    from hyperspace_tpu.plan.nodes import AggSpec
    from hyperspace_tpu.plan.schema import Field, Schema

    rng = np.random.default_rng(13)
    n = 5_000
    table = pa.table({
        "a": rng.integers(0, 5, n).astype(np.int64),
        "b": rng.integers(0, 4, n).astype(np.int64),
        "c": rng.integers(0, 3, n).astype(np.int64),
        "v": rng.random(n),
    })
    batch = columnar.from_arrow(table, device=True)
    specs = [AggSpec("sum", "v", "s")]
    out_schema = Schema([Field("a", "int64", True), Field("b", "int64", True),
                         Field("c", "int64", True),
                         Field("s", "float64", True)])
    orig = hp._fmix32
    agg_mod._group_phase_a_hashed.clear_cache()
    hp._fmix32 = lambda h: h * 0  # every key collides
    try:
        got = columnar.to_arrow(agg_mod.group_aggregate(
            batch, ["a", "b", "c"], specs, out_schema)).to_pandas()
    finally:
        hp._fmix32 = orig
        agg_mod._group_phase_a_hashed.clear_cache()
    want = (table.to_pandas().groupby(["a", "b", "c"], as_index=False)
            .agg(s=("v", "sum")))
    import pandas as pd
    key = ["a", "b", "c"]
    pd.testing.assert_frame_equal(
        got.sort_values(key).reset_index(drop=True),
        want.sort_values(key).reset_index(drop=True), check_dtype=False)


def test_hashed_counting_match_matches_exact():
    """Wide join keys (>=4 lanes) route through the hashed counting
    match; the join result must equal the exact multi-lane sort path."""
    import numpy as np
    import pyarrow as pa

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops import join as join_mod

    rng = np.random.default_rng(21)
    n, m = 20_000, 15_000
    left = columnar.from_arrow(pa.table({
        "k1": rng.integers(0, 50, n).astype(np.int64),
        "k2": rng.integers(-20, 20, n).astype(np.int64),
        "v": rng.random(n)}), device=True)
    right = columnar.from_arrow(pa.table({
        "k1": rng.integers(0, 50, m).astype(np.int64),
        "k2": rng.integers(-20, 20, m).astype(np.int64),
        "w": rng.random(m)}), device=True)
    # marker + 2x int64 lanes = 5 >= HASH_MATCH_MIN_LANES
    assert 5 >= join_mod.HASH_MATCH_MIN_LANES
    for how in ("inner", "left_outer"):
        li, ri = join_mod.counting_join_batch_indices(
            left, right, ["k1", "k2"], ["k1", "k2"], how=how)
        old = join_mod.HASH_MATCH_MIN_LANES
        join_mod.HASH_MATCH_MIN_LANES = 10**9
        try:
            li2, ri2 = join_mod.counting_join_batch_indices(
                left, right, ["k1", "k2"], ["k1", "k2"], how=how)
        finally:
            join_mod.HASH_MATCH_MIN_LANES = old
        got = sorted(zip(np.asarray(li).tolist(), np.asarray(ri).tolist()))
        want = sorted(zip(np.asarray(li2).tolist(),
                          np.asarray(ri2).tolist()))
        assert got == want, how


def test_hashed_counting_match_collision_fallback():
    """A degenerate hash (every key collides) must trigger the exact
    re-run, not a wrong join."""
    import numpy as np
    import pyarrow as pa

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops import hash_partition as hp
    from hyperspace_tpu.ops import join as join_mod

    rng = np.random.default_rng(22)
    n, m = 3_000, 2_500
    left = columnar.from_arrow(pa.table({
        "k1": rng.integers(0, 20, n).astype(np.int64),
        "k2": rng.integers(0, 10, n).astype(np.int64)}), device=True)
    right = columnar.from_arrow(pa.table({
        "k1": rng.integers(0, 20, m).astype(np.int64),
        "k2": rng.integers(0, 10, m).astype(np.int64)}), device=True)
    li2, ri2 = join_mod.counting_join_batch_indices(
        left, right, ["k1", "k2"], ["k1", "k2"], how="inner")
    orig = hp._fmix32
    join_mod._counting_match_lanes_hashed.clear_cache()
    hp._fmix32 = lambda h: h * 0
    try:
        li, ri = join_mod.counting_join_batch_indices(
            left, right, ["k1", "k2"], ["k1", "k2"], how="inner")
    finally:
        hp._fmix32 = orig
        join_mod._counting_match_lanes_hashed.clear_cache()
    got = sorted(zip(np.asarray(li).tolist(), np.asarray(ri).tolist()))
    want = sorted(zip(np.asarray(li2).tolist(), np.asarray(ri2).tolist()))
    assert got == want
