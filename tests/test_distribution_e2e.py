"""E2E with mesh distribution ON (the 8-device virtual CPU mesh): the same
create -> query -> assert flow as tests/test_e2e.py, with
`spark.hyperspace.distribution.enabled=true` routing the build through
`parallel/build.distributed_build`, the bucketed SMJ through
`parallel/join.distributed_bucketed_join_indices`, and filters through
`parallel/scan.distributed_filter`. Zero result diffs vs rules-off is the
acceptance bar (reference `E2EHyperspaceRulesTests.scala:330-346`)."""

import glob
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.facade import Hyperspace
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.plan.expr import col


@pytest.fixture
def dist_env(tmp_path, sample_parquet):
    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "hyperspace.index.num.buckets": 8,  # divisible by the 8-way mesh
        "hyperspace.distribution.enabled": "true",
    })
    session = HyperspaceSession(conf)
    return session, Hyperspace(session), sample_parquet


def run_with_and_without(session, query_df, sort_cols):
    session.disable_hyperspace()
    plain = query_df.to_pandas().sort_values(sort_cols).reset_index(drop=True)
    session.enable_hyperspace()
    indexed = query_df.to_pandas().sort_values(sort_cols).reset_index(drop=True)
    session.disable_hyperspace()
    return plain, indexed


def test_distributed_build_layout_matches_single_chip(tmp_path,
                                                      sample_parquet):
    """The mesh build must produce byte-identical bucket contents to the
    single-chip build (same hash identity, same (bucket, keys) order)."""
    single = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh1"),
        "hyperspace.index.num.buckets": 8,
        "hyperspace.distribution.enabled": "false",
    }))
    dist = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh2"),
        "hyperspace.index.num.buckets": 8,
        "hyperspace.distribution.enabled": "true",
    }))
    cfg = IndexConfig("cmp", ["clicks"], ["id", "query"])
    Hyperspace(single).create_index(single.read_parquet(sample_parquet), cfg)
    Hyperspace(dist).create_index(dist.read_parquet(sample_parquet), cfg)

    def bucket_contents(session):
        data_dir = os.path.join(session.conf.system_path, "cmp", "v__=0")
        out = {}
        for f in glob.glob(os.path.join(data_dir, "part-*.parquet")):
            bucket = os.path.basename(f)[5:10]
            t = pq.read_table(f).to_pandas()
            out.setdefault(bucket, []).append(t)
        return {b: pd.concat(ts).reset_index(drop=True)
                for b, ts in out.items()}

    single_buckets = bucket_contents(single)
    dist_buckets = bucket_contents(dist)
    assert set(single_buckets) == set(dist_buckets)
    for b in single_buckets:
        # Same rows per bucket; within-bucket order may differ only among
        # equal keys (both sides are key-sorted).
        lhs = single_buckets[b].sort_values(list(lhs_cols := single_buckets[b].columns)).reset_index(drop=True)
        rhs = dist_buckets[b].sort_values(list(lhs_cols)).reset_index(drop=True)
        pd.testing.assert_frame_equal(lhs, rhs)
        assert single_buckets[b]["clicks"].is_monotonic_increasing
        assert dist_buckets[b]["clicks"].is_monotonic_increasing


def test_e2e_filter_query_distributed(dist_env):
    session, hs, src = dist_env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("dfilter", ["clicks"], ["id", "score"]))
    query = df.filter(col("clicks") == 42).select("id", "score")
    plain, indexed = run_with_and_without(session, query, ["id"])
    assert len(plain) > 0
    pd.testing.assert_frame_equal(plain, indexed)


def test_e2e_join_query_distributed(dist_env):
    session, hs, src = dist_env
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("djl", ["imprs"], ["id", "clicks"]))
    hs.create_index(df, IndexConfig("djr", ["imprs"], ["score"]))
    left = df.select("imprs", "id", "clicks")
    right = df.select("imprs", "score")
    query = left.join(right, on="imprs")
    plain, indexed = run_with_and_without(
        session, query, ["imprs", "id", "score"])
    assert len(plain) > 0
    pd.testing.assert_frame_equal(plain, indexed)


def test_e2e_semi_anti_distributed_bucketed(dist_env):
    """Semi/anti over an index pair ride the co-bucketed MESH membership
    path (round 4): the planner keeps their bucketed alignment and the
    executor routes `distributed_semi_anti_indices`. Results must equal
    rules-off, and the plan must be a bucketed SMJ with no Exchange."""
    from hyperspace_tpu.engine.physical import SortMergeJoinExec

    session, hs, src = dist_env
    # Broadcast would shortcut the small right side; pin it off to
    # exercise the bucketed membership (reference-E2E style).
    session.conf.set("hyperspace.broadcast.threshold", -1)
    df = session.read_parquet(src)
    hs.create_index(df, IndexConfig("dsl", ["imprs"], ["id", "clicks"]))
    hs.create_index(df, IndexConfig("dsr", ["imprs"], ["score", "id"]))
    left = df.select("imprs", "id", "clicks")
    # Selective membership side (only the imprs of three rows) so BOTH
    # semi and anti keep rows.
    right = df.select("imprs", "id", "score").filter(col("id") < 3) \
        .select("imprs", "score")
    for how in ("left_semi", "left_anti"):
        query = left.join(right, on="imprs", how=how)
        plain, indexed = run_with_and_without(
            session, query, ["imprs", "id"])
        assert len(plain) > 0
        pd.testing.assert_frame_equal(plain, indexed)
        session.enable_hyperspace()
        _, _, physical = query.explain_plans()
        session.disable_hyperspace()
        smj = [n for n in physical.collect()
               if isinstance(n, SortMergeJoinExec)]
        names = [type(n).__name__ for n in physical.collect()]
        assert smj and smj[0].bucketed and smj[0].how == how, names
        assert names.count("ExchangeExec") == 0


def test_distributed_filter_matches_single_chip(tmp_path):
    """Unit-level: `parallel.scan.distributed_filter` equals
    `engine.compiler.apply_filter` on nullable + string data."""
    from hyperspace_tpu.engine.compiler import apply_filter
    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.parallel.context import distribution_mesh
    from hyperspace_tpu.parallel.scan import distributed_filter
    from hyperspace_tpu.plan.expr import col

    rng = np.random.default_rng(3)
    n = 1003  # deliberately not a multiple of the mesh size
    table = pa.table({
        "x": pa.array([None if i % 13 == 0 else int(v)
                       for i, v in enumerate(rng.integers(0, 50, n))],
                      type=pa.int64()),
        "s": pa.array([f"g{int(v)}" for v in rng.integers(0, 5, n)]),
        "id": np.arange(n, dtype=np.int64),
    })
    batch = columnar.from_arrow(table)
    mesh = distribution_mesh(None)
    assert mesh is not None  # conftest provides 8 devices
    predicate = ((col("x") > 10) & (col("s") != "g3")) | col("x").is_null()
    got = columnar.to_arrow(distributed_filter(batch, predicate, mesh))
    want = columnar.to_arrow(apply_filter(batch, predicate))
    pd.testing.assert_frame_equal(got.to_pandas(), want.to_pandas())


def test_distributed_aggregate_query_e2e(dist_env):
    """Aggregate query on the 8-device mesh (distribution forced on)
    equals the single-chip result."""
    import pandas as pd
    session, hs, src = dist_env
    df = session.read_parquet(src)

    def run():
        return (df.group_by("clicks").agg(("count", "*", "cnt"),
                                          ("sum", "imprs", "si"),
                                          ("avg", "score", "avs"))
                .collect().to_pandas().sort_values("clicks")
                .reset_index(drop=True))

    session.conf.set("spark.hyperspace.distribution.enabled", "true")
    session.conf.set("spark.hyperspace.execution.min.device.rows", "0")
    try:
        dist = run()
    finally:
        session.conf.set("spark.hyperspace.distribution.enabled", "false")
        session.conf.unset("spark.hyperspace.execution.min.device.rows")
    single = run()
    pd.testing.assert_frame_equal(dist, single, check_dtype=False,
                                  check_exact=False, rtol=1e-12)
