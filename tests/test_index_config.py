"""IndexConfig validation + builder (reference `IndexConfigTests`)."""

import pytest

from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.index_config import IndexConfig


def test_basic_construction():
    cfg = IndexConfig("idx", ["a", "b"], ["c"])
    assert cfg.index_name == "idx"
    assert cfg.indexed_columns == ["a", "b"]
    assert cfg.included_columns == ["c"]


@pytest.mark.parametrize("name,indexed,included", [
    ("", ["a"], []),
    ("  ", ["a"], []),
    ("idx", [], []),
    ("idx", ["a", "A"], []),          # duplicate indexed (case-insensitive)
    ("idx", ["a"], ["b", "B"]),       # duplicate included
    ("idx", ["a"], ["A"]),            # overlap indexed/included
])
def test_invalid_configs(name, indexed, included):
    with pytest.raises(HyperspaceException):
        IndexConfig(name, indexed, included)


def test_case_insensitive_equality():
    assert IndexConfig("IDX", ["A"], ["b"]) == IndexConfig("idx", ["a"], ["B"])
    assert IndexConfig("idx", ["a"], ["b", "c"]) == IndexConfig("idx", ["a"], ["c", "b"])
    assert IndexConfig("idx", ["a", "b"], []) != IndexConfig("idx", ["b", "a"], [])


def test_builder():
    cfg = (IndexConfig.builder()
           .index_name("idx")
           .index_by("a", "b")
           .include("c")
           .create())
    assert cfg == IndexConfig("idx", ["a", "b"], ["c"])


def test_builder_rejects_double_set():
    b = IndexConfig.builder().index_name("idx")
    with pytest.raises(HyperspaceException):
        b.index_name("other")
    b.index_by("a")
    with pytest.raises(HyperspaceException):
        b.index_by("b")


def test_builder_requires_name_and_columns():
    with pytest.raises(HyperspaceException):
        IndexConfig.builder().index_by("a").create()
    with pytest.raises(HyperspaceException):
        IndexConfig.builder().index_name("x").create()
