"""Action FSM tests against in-memory fakes (reference test layer 2:
`ActionTest`, `CreateActionTest`, Delete/Restore/Vacuum/Cancel tests)."""

import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.actions.base import Action
from hyperspace_tpu.actions.cancel import CancelAction
from hyperspace_tpu.actions.delete import DeleteAction
from hyperspace_tpu.actions.restore import RestoreAction
from hyperspace_tpu.actions.vacuum import VacuumAction

from fakes import FakeDataManager, FakeLogManager, make_entry


class NoOpAction(Action):
    """Minimal concrete action to test the template method."""

    transient_state = States.CREATING
    final_state = States.ACTIVE

    def __init__(self, log_manager):
        super().__init__(log_manager)
        self.op_ran = False

    def log_entry(self):
        return make_entry(state="")

    def op(self):
        self.op_ran = True


def test_action_writes_begin_then_end():
    """Parity with reference `ActionTest.scala:51-59`: with an empty log,
    begin writes id 0 (transient) and end writes id 1 (final) + latestStable."""
    mgr = FakeLogManager()
    action = NoOpAction(mgr)
    action.run()
    assert action.op_ran
    assert mgr.writes == [(0, States.CREATING), (1, States.ACTIVE)]
    assert mgr.stable_id == 1


def test_action_ids_continue_from_base():
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=States.CREATING))
    mgr.write_log(1, make_entry(state=States.ACTIVE))
    mgr.writes.clear()
    NoOpAction(mgr).run()
    assert mgr.writes == [(2, States.CREATING), (3, States.ACTIVE)]


def test_action_begin_conflict_raises():
    """Losing the OCC race on begin raises — exactly one concurrent actor
    can win log id base+1."""
    mgr = FakeLogManager()
    action = NoOpAction(mgr)
    # Simulate a concurrent writer taking id 0 after base_id was computed.
    _ = action.base_id
    mgr.write_log(0, make_entry(state=States.REFRESHING))
    with pytest.raises(HyperspaceException):
        action.run()


def test_delete_from_active():
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=States.CREATING))
    mgr.write_log(1, make_entry(state=States.ACTIVE))
    mgr.writes.clear()
    DeleteAction(mgr).run()
    assert mgr.writes == [(2, States.DELETING), (3, States.DELETED)]


@pytest.mark.parametrize("state", [States.CREATING, States.DELETED,
                                   States.DOESNOTEXIST])
def test_delete_invalid_states(state):
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=state))
    with pytest.raises(HyperspaceException):
        DeleteAction(mgr).run()


def test_restore_from_deleted():
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=States.DELETED))
    mgr.writes.clear()
    RestoreAction(mgr).run()
    assert mgr.writes == [(1, States.RESTORING), (2, States.ACTIVE)]


def test_restore_invalid_from_active():
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=States.ACTIVE))
    with pytest.raises(HyperspaceException):
        RestoreAction(mgr).run()


def test_vacuum_deletes_all_versions_latest_first():
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=States.DELETED))
    mgr.writes.clear()
    data = FakeDataManager(versions=[0, 1, 2])
    VacuumAction(mgr, data).run()
    assert mgr.writes == [(1, States.VACUUMING), (2, States.DOESNOTEXIST)]
    assert data.deleted == [2, 1, 0]


def test_vacuum_requires_deleted():
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=States.ACTIVE))
    with pytest.raises(HyperspaceException):
        VacuumAction(mgr, FakeDataManager()).run()


def test_cancel_restores_last_stable_state():
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=States.CREATING))
    mgr.write_log(1, make_entry(state=States.ACTIVE))
    mgr.write_log(2, make_entry(state=States.REFRESHING))
    mgr.writes.clear()
    CancelAction(mgr).run()
    assert mgr.writes == [(3, States.CANCELLING), (4, States.ACTIVE)]


def test_cancel_without_stable_goes_doesnotexist():
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=States.CREATING))
    mgr.writes.clear()
    CancelAction(mgr).run()
    assert mgr.writes == [(1, States.CANCELLING), (2, States.DOESNOTEXIST)]


def test_cancel_after_vacuuming_goes_doesnotexist():
    """Reference `CancelAction.scala:43-52`: VACUUMING -> DOESNOTEXIST since
    data may be partially deleted."""
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=States.VACUUMING))
    mgr.stable_id = 0
    # Force the stable log itself to be the VACUUMING record.
    mgr.writes.clear()
    CancelAction(mgr).run()
    assert mgr.writes == [(1, States.CANCELLING), (2, States.DOESNOTEXIST)]


@pytest.mark.parametrize("state", [States.ACTIVE, States.DELETED,
                                   States.DOESNOTEXIST])
def test_cancel_invalid_from_stable(state):
    mgr = FakeLogManager()
    mgr.write_log(0, make_entry(state=state))
    with pytest.raises(HyperspaceException):
        CancelAction(mgr).run()


def test_cancel_restores_stable_entry_content():
    """A cancelled refresh must republish the *stable* entry's metadata —
    content.root must not point at the partially-written new version dir."""
    mgr = FakeLogManager()
    active = make_entry(state=States.ACTIVE, root="/idx/v__=0")
    mgr.write_log(0, active)
    mgr.stable_id = 0
    refreshing = make_entry(state=States.REFRESHING, root="/idx/v__=1")
    mgr.write_log(1, refreshing)
    CancelAction(mgr).run()
    final = mgr.get_latest_log()
    assert final.state == States.ACTIVE
    assert final.content.root == "/idx/v__=0"
