"""Memory, cache, and compile observability (PR 3): the device-memory
accountant (per-device peaks on the virtual 8-device mesh, per-query
watermarks), byte-budget cache eviction, jit compile/retrace tracking,
Perfetto counter tracks, the leak sentinel, and the peak-HBM bench
gate."""

import gc
import json
import os
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import telemetry
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine import fusion
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.plan.expr import col, lit

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tracing():
    tracer = telemetry.enable_tracing()
    try:
        yield tracer
    finally:
        telemetry.disable_tracing()


@pytest.fixture
def sales_env(tmp_path):
    """One fact table + a session factory (device lane forced)."""
    rng = np.random.default_rng(7)
    n = 4000
    fact_dir = tmp_path / "fact"
    fact_dir.mkdir()
    pq.write_table(pa.table({
        "key": rng.integers(0, 100, n).astype(np.int64),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": rng.random(n) * 100,
    }), str(fact_dir / "part-0.parquet"))

    def session(**extra):
        conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh"),
                "spark.hyperspace.execution.min.device.rows": "0",
                "spark.hyperspace.distribution.enabled": "false"}
        conf.update(extra)
        return HyperspaceSession(HyperspaceConf(conf))

    return session, str(fact_dir)


# ---------------------------------------------------------------------------
# Device-memory accountant
# ---------------------------------------------------------------------------


def test_accountant_per_device_attribution():
    """live-arrays fallback on the virtual mesh: bytes placed on ONE
    device show up on THAT device's gauge and in the recording query's
    per-device watermark."""
    import jax

    devices = jax.devices()
    assert len(devices) >= 8  # conftest's virtual mesh
    payload = np.ones(1 << 16, dtype=np.float64)  # 512 KiB
    held = jax.device_put(payload, devices[3])
    held.block_until_ready()
    label = f"{devices[3].platform}:{devices[3].id}"
    rec = telemetry.QueryMetrics("mem attribution")
    with telemetry.recording(rec):
        live = telemetry.memory.sample()
    assert live is not None and live.get(label, 0) >= payload.nbytes
    assert rec.peak_hbm_per_device[label] >= payload.nbytes
    assert rec.peak_hbm_bytes >= payload.nbytes
    reg = telemetry.get_registry()
    assert reg.gauge(f"memory.{label}.bytes_in_use").value \
        >= payload.nbytes
    assert reg.gauge(f"memory.{label}.peak_bytes").value >= payload.nbytes
    snap = telemetry.memory.snapshot()
    assert snap["backend"] == "live_arrays"  # no memory_stats on CPU
    assert snap["devices"][label]["peak_bytes"] >= payload.nbytes
    assert snap["peak_hbm_bytes"] >= payload.nbytes
    del held


def test_maybe_sample_noop_without_consumers():
    acct = telemetry.get_accountant()
    before = acct.samples
    assert telemetry.current() is None and telemetry.tracer() is None
    telemetry.memory.maybe_sample()
    assert acct.samples == before


def test_query_metrics_peak_and_compile_fields(sales_env):
    session, fact_dir = sales_env
    sess = session()
    q = lambda: sess.read_parquet(fact_dir).filter(  # noqa: E731
        col("qty") > lit(10)).select("key", "price")
    q().collect()  # warm: traces, promotes, caches
    _, warm = q().collect(with_metrics=True)
    assert warm.peak_hbm_bytes > 0
    assert warm.peak_hbm_per_device
    # Re-running the SAME query causes ZERO new traces (the acceptance
    # bar: a warm query must be retrace-free), while the jit cache
    # serves the dispatches.
    assert warm.compile["traces"] == 0, (
        f"warm rerun re-traced: {warm.events_of('compile')}")
    assert warm.compile["cache_hits"] >= 1
    d = warm.to_dict()
    assert d["peak_hbm_bytes"] == warm.peak_hbm_bytes
    assert d["compile"]["traces"] == 0
    assert "peak_hbm_bytes" in warm.summary()
    tree = warm.format_tree()
    assert "Peak HBM:" in tree and "Compile:" in tree


# ---------------------------------------------------------------------------
# Byte-budget cache eviction
# ---------------------------------------------------------------------------


@pytest.fixture
def promote_cache():
    """Isolated fusion promotion cache with restored budget."""
    saved_budget = fusion._promote_budget[0]
    saved = dict(fusion._promote_cache)
    fusion._promote_cache.clear()
    try:
        yield fusion._promote_cache
    finally:
        fusion._promote_budget[0] = saved_budget
        fusion._promote_cache.clear()
        fusion._promote_cache.update(saved)


def test_promote_cache_byte_budget_eviction_order(promote_cache):
    arrays = [np.arange(100, dtype=np.float64) + i for i in range(4)]
    nbytes = arrays[0].nbytes  # 800
    fusion._promote_budget[0] = int(nbytes * 2.5)  # room for two
    reg = telemetry.get_registry()
    ev_before = reg.counter("cache.fusion_promote.evictions").value
    for a in arrays:
        fusion._to_device(a)
    tokens = [fusion._token_of(a) for a in arrays]
    held = [t for t in tokens if t in promote_cache]
    # Oldest-inserted evicted first: the survivors are exactly the
    # newest entries that fit the byte budget.
    assert held == tokens[2:]
    assert reg.counter("cache.fusion_promote.evictions").value \
        == ev_before + 2
    assert reg.gauge("cache.fusion_promote.bytes_held").value \
        <= fusion._promote_budget[0]
    assert reg.gauge("cache.fusion_promote.entries").value == 2


def test_promote_cache_sweeps_dead_refs_on_insert(promote_cache):
    """A GC'd host source must not linger holding its device buffer
    until byte pressure (the silent HBM leak): the dead entry is swept
    on the NEXT insert, budget headroom or not. (On CPU backends
    `device_put` may zero-copy-alias the host buffer, keeping the
    source alive through the cached device array — so a dead entry is
    planted directly rather than via real GC.)"""
    import weakref

    fusion._promote_budget[0] = 1 << 30
    a = np.arange(64, dtype=np.float64)
    dev = fusion._to_device(a)
    assert len(promote_cache) == 1

    class _Src:
        pass

    src = _Src()
    promote_cache[-99] = (weakref.ref(src), dev)
    del src
    gc.collect()
    assert promote_cache[-99][0]() is None  # entry is dead
    b = np.arange(32, dtype=np.float64)
    fusion._to_device(b)
    assert -99 not in promote_cache  # dead entry swept on insert
    assert fusion._token_of(a) in promote_cache
    assert fusion._token_of(b) in promote_cache


def test_promote_cache_hit_miss_series(promote_cache):
    fusion._promote_budget[0] = 1 << 30
    reg = telemetry.get_registry()
    hits0 = reg.counter("cache.fusion_promote.hits").value
    miss0 = reg.counter("cache.fusion_promote.misses").value
    a = np.arange(128, dtype=np.float64)
    d1 = fusion._to_device(a)
    d2 = fusion._to_device(a)
    assert d1 is d2  # served from cache, no second transfer
    assert reg.counter("cache.fusion_promote.misses").value == miss0 + 1
    assert reg.counter("cache.fusion_promote.hits").value == hits0 + 1


def test_parquet_device_cache_series(sales_env):
    """The device read lane is the HBM segment cache (`io/segcache.py`)
    — repeat device scans hit it and report the `cache.segments.*`
    series."""
    session, fact_dir = sales_env
    sess = session()
    reg = telemetry.get_registry()
    miss0 = reg.counter("cache.segments.misses").value
    hits0 = reg.counter("cache.segments.hits").value
    q = lambda: sess.read_parquet(fact_dir).select("key")  # noqa: E731
    q().collect()
    q().collect()
    assert reg.counter("cache.segments.misses").value > miss0
    assert reg.counter("cache.segments.hits").value > hits0
    assert reg.gauge("cache.segments.bytes_held").value > 0
    assert reg.gauge("cache.segments.entries").value >= 1


# ---------------------------------------------------------------------------
# Index metadata cache: monotonic clock + series
# ---------------------------------------------------------------------------


def test_index_metadata_cache_monotonic(monkeypatch, conf):
    from hyperspace_tpu.index import cache as index_cache

    cache = index_cache.CreationTimeBasedCache(conf)  # expiry 300 s
    reg = telemetry.get_registry()
    hits0 = reg.counter("cache.index_metadata.hits").value
    ev0 = reg.counter("cache.index_metadata.evictions").value
    cache.set("entry")
    # A wall-clock jump (NTP step, manual change) must NOT expire the
    # entry: expiry is a duration, measured on the monotonic clock.
    real_time = time.time
    monkeypatch.setattr(index_cache.time, "time",
                        lambda: real_time() + 10_000)
    assert cache.get() == "entry"
    assert reg.counter("cache.index_metadata.hits").value == hits0 + 1
    # Monotonic advance past the expiry DOES.
    real_mono = time.monotonic
    monkeypatch.setattr(index_cache.time, "monotonic",
                        lambda: real_mono() + 301)
    assert cache.get() is None
    assert reg.counter("cache.index_metadata.evictions").value == ev0 + 1
    assert reg.gauge("cache.index_metadata.entries").value == 0


# ---------------------------------------------------------------------------
# Compile observability
# ---------------------------------------------------------------------------


def test_instrumented_jit_retrace_agreement():
    """Our trace counter must agree with jax's OWN executable-cache
    size — the counter is only trustworthy if it counts exactly the
    traces XLA performed."""
    import jax.numpy as jnp

    from hyperspace_tpu.telemetry.compilation import instrumented_jit

    name = "test.retrace_agreement"
    fn = instrumented_jit(name)(lambda x: x * 2)
    reg = telemetry.get_registry()
    base = reg.counter(f"compile.{name}.traces").value
    rec = telemetry.QueryMetrics("retrace probe")
    with telemetry.recording(rec):
        fn(jnp.ones(8))                       # trace 1 (first)
        fn(jnp.ones(8))                       # executable-cache hit
        fn(jnp.ones(16))                      # trace 2 (shape delta)
        fn(jnp.ones(16, dtype=jnp.int64))     # trace 3 (dtype delta)
    assert reg.counter(f"compile.{name}.traces").value == base + 3
    jax_count = fn.cache_size()
    if jax_count is not None:  # agreement with jax's trace count
        assert jax_count == 3
    assert rec.compile["traces"] == 3
    assert rec.compile["cache_hits"] == 1
    assert rec.compile["seconds"] > 0
    events = rec.events_of("compile")
    assert len(events) == 3
    assert events[0]["name"] == "trace"
    assert events[0]["cause"] == "first trace"
    # Retrace causes name the shape/dtype signature delta.
    assert events[1]["name"] == "retrace"
    assert "[8]" in events[1]["cause"] and "[16]" in events[1]["cause"]
    assert "int64" in events[2]["cause"]
    assert getattr(fn, "__compile_span_instrumented__", False)


def test_compile_span_lands_in_trace(tracing):
    import jax.numpy as jnp

    from hyperspace_tpu.telemetry.compilation import instrumented_jit

    fn = instrumented_jit("test.compile_span")(lambda x: x + 1)
    fn(jnp.ones(4))
    spans = [e for e in tracing.events
             if e["ph"] == "X" and e.get("cat") == "compile"]
    assert spans and spans[-1]["args"]["target"] == "test.compile_span"


def test_coverage_lint_flags_raw_jit(tmp_path):
    """The source lint behind check_metrics_coverage: a direct jax.jit
    call is a jit entry point without the compile-span stamp."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        from check_metrics_coverage import check_jit_entry_points
    finally:
        sys.path.pop(0)
    pkg = tmp_path / "pkg"
    (pkg / "telemetry").mkdir(parents=True)
    (pkg / "ok.py").write_text(
        "from hyperspace_tpu.telemetry import instrumented_jit\n"
        "# mentions jax.jit in prose only\n")
    (pkg / "bad.py").write_text(
        "import jax\n\n\ndef f(x):\n    return jax.jit(lambda y: y)(x)\n")
    failures = check_jit_entry_points(str(pkg))
    assert len(failures) == 1 and "bad.py" in failures[0]
    # ...and the shipped package itself is clean (no raw jax.jit).
    import hyperspace_tpu
    shipped = check_jit_entry_points(
        os.path.dirname(hyperspace_tpu.__file__))
    assert shipped == [], shipped


# ---------------------------------------------------------------------------
# Perfetto counter tracks
# ---------------------------------------------------------------------------


def test_trace_export_has_memory_counter_tracks(sales_env, tmp_path,
                                                tracing):
    session, fact_dir = sales_env
    sess = session()
    sess.read_parquet(fact_dir).filter(
        col("qty") > lit(5)).select("price").collect()
    path = str(tmp_path / "trace.json")
    telemetry.export_trace(path)
    with open(path) as f:
        doc = json.load(f)
    counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert counters, "no counter-track events in the export"
    hbm = [e for e in counters if e["name"].startswith("HBM ")]
    assert hbm
    for ev in hbm:
        assert ev["args"]["bytes_in_use"] >= 0
        assert isinstance(ev["ts"], (int, float))


# ---------------------------------------------------------------------------
# Leak sentinel
# ---------------------------------------------------------------------------


def test_no_device_array_leak_across_repeat_queries(sales_env,
                                                    leak_sentinel):
    session, fact_dir = sales_env
    sess = session()
    q = lambda: sess.read_parquet(fact_dir).filter(  # noqa: E731
        col("qty") > lit(10)).select("key", "price")
    for _ in range(2):
        q().collect()  # warm: executables, promote + device caches
    with leak_sentinel():
        for _ in range(3):
            q().collect()


# ---------------------------------------------------------------------------
# Artifact section + bench_regress peak-HBM gate
# ---------------------------------------------------------------------------


def test_artifact_section_shape(sales_env):
    session, fact_dir = sales_env
    sess = session()
    sess.read_parquet(fact_dir).filter(
        col("qty") > lit(1)).select("key").collect()
    section = telemetry.memory.artifact_section()
    assert section["peak_hbm_bytes"] > 0
    assert section["devices"]
    assert "segments" in section["caches"]
    series = section["caches"]["segments"]
    assert {"hits", "misses", "evictions", "bytes_held",
            "entries"} <= set(series)
    assert section["compile"].get("traces", 0) >= 1
    assert section["compile"].get("cache_hits", 0) >= 0


def _write_artifact(path, headline, peak_hbm=None):
    # Canonical-schema fixture; a round MAY predate the memory
    # section (peak_hbm=None) and must then not gate on it.
    doc = {"schema_version": 1, "metric": "fixture", "value": 1.0,
           "process_metrics": {},
           "vs_baseline": headline,
           "rungs": {"1_build": {"vs_baseline": headline}}}
    if peak_hbm is not None:
        doc["memory"] = {"peak_hbm_bytes": peak_hbm}
    with open(path, "w") as f:
        json.dump(doc, f)


def test_bench_regress_gates_on_peak_hbm(tmp_path):
    script = os.path.join(REPO_ROOT, "scripts", "bench_regress.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    old = str(tmp_path / "BENCH_r01.json")
    ok = str(tmp_path / "BENCH_r02.json")
    bad = str(tmp_path / "BENCH_r03.json")
    legacy = str(tmp_path / "BENCH_r00.json")
    _write_artifact(old, 2.0, peak_hbm=1_000_000)
    _write_artifact(ok, 2.0, peak_hbm=1_100_000)    # +10%: passes
    _write_artifact(bad, 2.0, peak_hbm=1_600_000)   # +60%: fails
    _write_artifact(legacy, 2.0)                    # no memory: no gate
    good = subprocess.run([sys.executable, script, old, ok],
                          capture_output=True, text=True, env=env)
    assert good.returncode == 0, good.stdout + good.stderr
    assert "peak_hbm_bytes" in good.stdout
    regress = subprocess.run([sys.executable, script, old, bad],
                             capture_output=True, text=True, env=env)
    assert regress.returncode == 1
    assert "peak_hbm_bytes" in regress.stderr
    # Wall-time regressions still gate in BOTH directions of the ratio.
    _write_artifact(bad, 1.0, peak_hbm=1_000_000)
    slow = subprocess.run([sys.executable, script, old, bad],
                          capture_output=True, text=True, env=env)
    assert slow.returncode == 1
    # Artifacts predating the memory section never gate on it.
    legacy_run = subprocess.run([sys.executable, script, legacy, old],
                                capture_output=True, text=True, env=env)
    assert legacy_run.returncode == 0, legacy_run.stdout + legacy_run.stderr
