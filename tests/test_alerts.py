"""Incident plane: rule sustain/hysteresis against scripted series,
SLO-burn chaos firing exactly ONE evidence-bundled incident, durable
history segments surviving a crash-torn writer, and cross-process
history merge into one CLI trend report."""

import json
import os
import time

import pytest

from hyperspace_tpu import telemetry
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine import scheduler as sched_mod
from hyperspace_tpu.engine.scheduler import QueryScheduler
from hyperspace_tpu.telemetry import alerts, history, timeseries
from hyperspace_tpu.telemetry.alerts import AlertManager, AlertRule
from hyperspace_tpu.telemetry.history import TelemetryHistory
from hyperspace_tpu.telemetry.timeseries import TimeSeriesSampler


@pytest.fixture
def fresh_scheduler():
    sch = sched_mod.set_scheduler(QueryScheduler())
    yield sch
    sched_mod.set_scheduler(QueryScheduler())


@pytest.fixture
def no_history():
    """Tests that must not write segments anywhere."""
    prev = history.get_history()
    history.reset_history()
    yield
    history.set_history(prev)


def _counters(*names):
    c = telemetry.get_registry().counters_dict()
    return tuple(c.get(n, 0) for n in names)


# ---------------------------------------------------------------------------
# Sustain + hysteresis against a scripted series
# ---------------------------------------------------------------------------


def test_sustain_and_hysteresis_scripted_gauge(no_history):
    """The full lifecycle, driven tick-by-tick with scripted times: a
    breach must HOLD for sustain_s (one hiccup resets the clock), a
    firing rule resolves only across `clear` (the hysteresis band
    between clear and threshold neither resolves nor suppresses), and
    the counters agree exactly."""
    reg = telemetry.get_registry()
    g = reg.gauge("testx.alerts.gauge")
    rule = AlertRule("test_gauge", "gauge", "testx.alerts.gauge",
                     threshold=10.0, clear=5.0, sustain_s=3.0,
                     description="scripted")
    m = AlertManager(rules=[rule])
    ev0, f0, r0, s0 = _counters("alerts.evaluations", "alerts.fired",
                                "alerts.resolved", "alerts.suppressed")

    g.set(20.0)
    assert m.evaluate(now=100.0) == []      # breach starts, not sustained
    g.set(4.0)
    assert m.evaluate(now=101.0) == []      # hiccup: sustain clock reset
    g.set(20.0)
    assert m.evaluate(now=102.0) == []      # breach restarts
    assert m.evaluate(now=104.9) == []      # 2.9s held < 3s sustain
    fired = m.evaluate(now=105.1)           # 3.1s held: fires
    assert len(fired) == 1
    assert fired[0]["rule"] == "test_gauge"
    assert fired[0]["state"] == "firing"
    assert m.active_count() == 1

    g.set(7.0)                              # hysteresis band (5 < 7 < 10)
    assert m.evaluate(now=106.0) == []      # neither resolved nor breach
    g.set(20.0)
    assert m.evaluate(now=107.0) == []      # repeat breach: suppressed
    g.set(4.0)
    resolved = m.evaluate(now=108.0)        # crosses clear: resolves
    assert len(resolved) == 1
    assert resolved[0]["state"] == "resolved"
    assert resolved[0]["resolved_at"] == 108.0
    assert resolved[0]["id"] == fired[0]["id"]
    assert m.active_count() == 0

    ev, f, r, s = _counters("alerts.evaluations", "alerts.fired",
                            "alerts.resolved", "alerts.suppressed")
    assert (ev - ev0, f - f0, r - r0, s - s0) == (8, 1, 1, 1)
    # The exact-agreement contract, post-lifecycle.
    assert (f - f0) - (r - r0) == m.active_count() == 0
    assert reg.to_dict()["gauges"]["alerts.active"] == 0


def test_window_delta_rule_fires_and_decays_with_scripted_ticks(
        no_history):
    """A breaker-open-shaped rule (window_delta, sustain 0) against a
    scripted sampler: the delta fires on the tick that sees the
    increment and resolves once the window slides past it."""
    reg = telemetry.get_registry()
    c = reg.counter("testx.alerts.opened")
    sampler = TimeSeriesSampler(interval_s=1.0, capacity=64,
                                window_s=4.0,
                                counter_prefixes=("testx.",))
    rule = AlertRule("test_breaker", "window_delta",
                     "testx.alerts.opened", threshold=0.0, clear=0.5,
                     sustain_s=0.0, description="scripted breaker")
    m = AlertManager(rules=[rule])

    sampler.tick(t=200.0)
    assert m.evaluate(sampler=sampler, now=200.0) == []
    c.inc()
    sampler.tick(t=201.0)
    fired = m.evaluate(sampler=sampler, now=201.0)
    assert len(fired) == 1 and fired[0]["state"] == "firing"
    assert fired[0]["value"] == 1.0
    # The window still covers the increment: suppressed, not re-fired.
    sampler.tick(t=202.0)
    assert m.evaluate(sampler=sampler, now=202.0) == []
    # Slide past the 4s window: delta decays to 0 < clear, resolves.
    for t in (203.0, 204.0, 205.0, 206.0, 207.0):
        sampler.tick(t=t)
    resolved = m.evaluate(sampler=sampler, now=207.0)
    assert len(resolved) == 1 and resolved[0]["state"] == "resolved"
    sampler.drain()


def test_conf_overrides_disable_and_retune(no_history):
    reg = telemetry.get_registry()
    g = reg.gauge("testx.alerts.gauge2")
    rule = AlertRule("test_tune", "gauge", "testx.alerts.gauge2",
                     threshold=10.0, clear=5.0, sustain_s=0.0,
                     description="tunable")
    g.set(20.0)

    # Per-rule kill switch.
    m = AlertManager(rules=[rule])
    off = HyperspaceConf({
        "spark.hyperspace.telemetry.alerts.rule.test_tune.enabled":
            "false"})
    assert m.evaluate(conf=off, now=1.0) == []
    assert m.active_count() == 0

    # Threshold override: 20 no longer breaches a threshold of 50.
    m2 = AlertManager(rules=[rule])
    tuned = HyperspaceConf({
        "spark.hyperspace.telemetry.alerts.rule.test_tune.threshold":
            "50", })
    assert m2.evaluate(conf=tuned, now=1.0) == []
    g.set(60.0)
    assert len(m2.evaluate(conf=tuned, now=2.0)) == 1

    # Global kill switch short-circuits evaluation entirely.
    m3 = AlertManager(rules=[rule])
    ev0 = _counters("alerts.evaluations")[0]
    killed = HyperspaceConf({
        "spark.hyperspace.telemetry.alerts.enabled": "false"})
    assert m3.evaluate(conf=killed, now=1.0) == []
    assert _counters("alerts.evaluations")[0] == ev0


# ---------------------------------------------------------------------------
# SLO-burn chaos: exactly ONE incident, with the full evidence bundle
# ---------------------------------------------------------------------------


def test_slo_burn_chaos_fires_one_evidence_bundled_incident(
        tmp_path, fresh_scheduler):
    """Inject a sustained SLO burn and drive the DEFAULT rule set:
    exactly one incident opens (repeat breaching ticks suppress), its
    evidence bundle carries registry snapshot + window quantiles +
    flight entries with critical paths + a device-capture path + SLO
    state, both transitions persist into the history store, and the
    burn decay resolves it with exact counter agreement."""
    sch = fresh_scheduler
    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "spark.hyperspace.serve.slo.p99.seconds": "0.01",
        "spark.hyperspace.serve.slo.window.seconds": "1.0",
        "spark.hyperspace.telemetry.profiler.capture.seconds": "0.05",
        "spark.hyperspace.telemetry.profiler.capture.min.interval."
        "seconds": "0",
    })
    hist_dir = tmp_path / "hist"
    prev_hist = history.get_history()
    history.set_history(TelemetryHistory(str(hist_dir), interval_s=1.0))
    prev_mgr = alerts.get_manager()
    m = alerts.set_manager(AlertManager())
    m.configure(conf)
    # Flight entries with stamped critical paths for the bundle.
    for i in range(2):
        qm = telemetry.QueryMetrics(description=f"burnq{i}")
        qm.finish()
        qm.critical_path = {"wall_s": 0.05,
                            "segments": {"host_python": 0.05}}
        telemetry.flight.get_recorder().record(qm)
    f0, r0, s0 = _counters("alerts.fired", "alerts.resolved",
                           "alerts.suppressed")
    try:
        # Chaos: every completed query violates the 10ms target.
        for _ in range(10):
            sch.slo.record(0.05, conf)
        t0 = time.time()
        assert m.evaluate(conf=conf, now=t0) == []        # sustain starts
        fired = m.evaluate(conf=conf, now=t0 + 3.5)       # past 3s sustain
        assert len(fired) == 1
        incident = fired[0]
        assert incident["rule"] == "slo_burn"
        assert incident["value"] > 1.0
        # Still burning: more ticks suppress, never duplicate.
        for dt in (4.0, 4.5, 5.0):
            assert m.evaluate(conf=conf, now=t0 + dt) == []
        f, r, s = _counters("alerts.fired", "alerts.resolved",
                            "alerts.suppressed")
        assert (f - f0, r - r0) == (1, 0)
        assert s - s0 >= 3
        assert m.active_count() == 1 == (f - f0) - (r - r0)

        # The evidence bundle is complete.
        ev = incident["evidence"]
        for key in ("registry", "window_quantiles", "flight", "slowlog",
                    "device_profile", "slo", "captured_at"):
            assert key in ev, key
        assert "counters" in ev["registry"]
        assert not isinstance(ev["flight"], dict)
        flights = {e["description"]: e for e in ev["flight"]}
        assert flights["burnq1"]["critical_path"]["segments"]
        assert ev["slowlog"]["kind"] == "hyperspace-slowlog"
        assert isinstance(ev["device_profile"], str)  # capture path
        assert ev["slo"]["window_violations"] >= 10

        # The firing transition persisted durably, reason "incident".
        segs, skipped = history.read_segments(str(hist_dir))
        assert skipped == 0
        fire_segs = [d for d in segs if d["reason"] == "incident"]
        assert len(fire_segs) == 1
        assert fire_segs[0]["incidents"][0]["id"] == incident["id"]

        # Recovery: the 1s burn window slides empty, refresh() decays
        # the gauge, the incident resolves.
        time.sleep(1.1)
        resolved = m.evaluate(conf=conf, now=t0 + 10.0)
        assert len(resolved) == 1
        assert resolved[0]["state"] == "resolved"
        assert resolved[0]["id"] == incident["id"]
        f, r, _s = _counters("alerts.fired", "alerts.resolved",
                             "alerts.suppressed")
        assert (f - f0) - (r - r0) == 0 == m.active_count()
        segs, _ = history.read_segments(str(hist_dir))
        states = [d["incidents"][0]["state"] for d in segs
                  if d["reason"] == "incident"]
        assert states == ["firing", "resolved"]

        # The digest bench artifacts embed reflects the same story.
        digest = m.digest()
        assert digest["active"] == 0
        assert digest["incidents"][-1]["rule"] == "slo_burn"
        assert digest["incidents"][-1]["state"] == "resolved"
    finally:
        alerts.set_manager(prev_mgr)
        history.set_history(prev_hist)


# ---------------------------------------------------------------------------
# Durable history: torn segments, pruning, cross-process merge
# ---------------------------------------------------------------------------


def test_history_survives_crash_torn_final_segment(tmp_path, conf):
    """Two clean segments + a torn final segment of a 'crashed' writer
    + a foreign json + a .tmp leftover: the reader keeps the clean
    pair, counts the torn/foreign skips, and the merge stays whole."""
    d = tmp_path / "hist"
    h = TelemetryHistory(str(d), interval_s=1.0)
    assert h.flush(conf=conf, reason="manual", now=1000.0)
    assert h.flush(conf=conf, reason="manual", now=1100.0)
    # A crash mid-write that somehow published half a document.
    (d / "history-1200000-42-000003.json").write_text(
        '{"kind": "hyperspace-telemetry-history", "schema_ver')
    # A foreign-but-parseable file someone dropped in the directory.
    (d / "history-1300000-42-000004.json").write_text(
        '{"kind": "not-ours"}')
    # The atomic-publish tmp of a writer that died pre-rename.
    (d / "history-1400000-42-000005.json.tmp").write_text("{")

    skipped0 = _counters("history.read_skipped")[0]
    segs, skipped = history.read_segments(str(d))
    assert len(segs) == 2
    assert skipped == 2          # torn + foreign; .tmp excluded by name
    assert _counters("history.read_skipped")[0] - skipped0 == 2
    assert [s["written_at"] for s in segs] == [1000.0, 1100.0]
    merged = history.merge(str(d))
    assert merged["segments"] == 2 and merged["skipped"] == 2
    report = history.trend_report(merged, window_s=300.0)
    assert report["samples"] == len(merged["samples"])


def test_history_byte_budget_prunes_oldest(tmp_path, conf):
    d = tmp_path / "hist"
    h = TelemetryHistory(str(d), interval_s=1.0, keep_seconds=0,
                         keep_bytes=1)  # everything but the newest
    p0 = _counters("history.segments_pruned")[0]
    h.flush(conf=conf, reason="manual", now=1000.0)
    h.flush(conf=conf, reason="manual", now=1001.0)
    h.flush(conf=conf, reason="manual", now=1002.0)
    names = sorted(f for f in os.listdir(str(d))
                   if f.endswith(".json"))
    assert len(names) == 1            # newest survives, always
    assert names[0].startswith("history-1002000-")
    assert _counters("history.segments_pruned")[0] - p0 == 2


@pytest.fixture
def scripted_global_sampler(no_history):
    """A fresh GLOBAL sampler (the history writer snapshots it), driven
    by explicit tick(t=...) calls only."""
    s = timeseries.set_sampler(
        TimeSeriesSampler(interval_s=1.0, capacity=64))
    yield s
    timeseries.reset_sampler()


def test_history_cross_process_merge_and_cli_report(
        tmp_path, conf, monkeypatch, capsys, scripted_global_sampler):
    """Two writer lifetimes (distinct pids) into one directory: the
    merge sees both writers, dedups the incident by id with the latest
    state winning, and the CLI renders ONE trend report over the
    combined history."""
    d = tmp_path / "hist"
    reg = telemetry.get_registry()
    incident = {"id": "inc-1-0001", "rule": "slo_burn",
                "state": "firing", "opened_at": 1000.0,
                "resolved_at": None, "value": 2.0, "threshold": 1.0}
    reg.counter("queries.total").inc(5)
    scripted_global_sampler.tick(t=1000.0)
    TelemetryHistory(str(d)).flush(conf=conf, reason="incident",
                                   now=1000.0, incidents=[incident])
    # "Another process" resumes the story and resolves the incident.
    monkeypatch.setattr(
        "hyperspace_tpu.telemetry.history.os.getpid", lambda: 9990042)
    reg.counter("queries.total").inc(7)
    scripted_global_sampler.tick(t=2000.0)
    done = dict(incident, state="resolved", resolved_at=2000.0)
    TelemetryHistory(str(d)).flush(conf=conf, reason="incident",
                                   now=2000.0, incidents=[done])

    merged = history.merge(str(d))
    assert merged["segments"] == 2
    assert len(merged["writers"]) == 2
    assert len(merged["incidents"]) == 1          # deduped by id
    assert merged["incidents"][0]["state"] == "resolved"
    assert len(merged["registry_by_pid"]) == 2
    report = history.trend_report(merged, window_s=3600.0,
                                  series=["queries.total"])
    assert "queries.total" in report["counters"]
    assert report["incidents"] == 1

    # One CLI report over both lifetimes.
    rc = history._main(["report", "--dir", str(d), "--series",
                        "queries.total"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["segments"] == 2
    assert len(doc["writers"]) == 2
    assert doc["incident_list"] == [
        {"id": "inc-1-0001", "rule": "slo_burn", "state": "resolved",
         "opened_at": 1000.0, "resolved_at": 2000.0, "value": 2.0,
         "threshold": 1.0}]
    assert "queries.total" in doc["counters"]


def test_history_cli_baseline_regression(tmp_path, conf, capsys,
                                         scripted_global_sampler):
    """`--baseline` regresses the history's latest cumulative counters
    against a committed canonical bench artifact."""
    from hyperspace_tpu.telemetry import artifact

    telemetry.get_registry().counter("queries.total").inc()
    scripted_global_sampler.tick(t=1000.0)
    d = tmp_path / "hist"
    TelemetryHistory(str(d)).flush(conf=conf, reason="manual",
                                   now=1000.0)
    doc = artifact.make_artifact(driver="bench.py", metric="wall_s",
                                 value=1.0, unit="s", vs_baseline=None)
    base = tmp_path / "BENCH_r01.json"
    base.write_text(json.dumps(doc))
    rc = history._main(["report", "--dir", str(d),
                        "--baseline", str(base)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    vs = out["vs_baseline"]
    assert vs["driver"] == "bench.py"
    assert "queries.total" in vs["counters"]
    row = vs["counters"]["queries.total"]
    assert row["history"] >= row["baseline"] > 0


# ---------------------------------------------------------------------------
# The false-positive gate in miniature: a clean lap fires nothing
# ---------------------------------------------------------------------------


def test_clean_closed_loop_lap_fires_zero_incidents(
        tmp_path, fresh_scheduler, no_history):
    """bench_serve.py's `clean_run_fired == 0` gate, in miniature: a
    healthy concurrent closed-loop lap with the GLOBAL alert manager
    live (the sampler's tick hook evaluating every default rule) must
    fire ZERO incidents — the plane evaluates, nothing alarms."""
    import threading

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu.engine.session import HyperspaceSession
    from hyperspace_tpu.plan.expr import col, lit

    rng = np.random.default_rng(3)
    src = tmp_path / "src"
    src.mkdir()
    pq.write_table(pa.table({
        "k": rng.integers(0, 100, 4000).astype(np.int64),
        "v": rng.random(4000),
    }), str(src / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        # SLO tracking live, with a target a healthy CPU lap meets.
        "spark.hyperspace.serve.slo.p99.seconds": "30",
    }))
    manager = alerts.set_manager(AlertManager())
    manager.configure(sess.conf)
    sampler = timeseries.set_sampler(
        TimeSeriesSampler(interval_s=0.05, capacity=256))
    try:
        df = sess.read_parquet(str(src))
        q = df.filter(col("k") == lit(7)).select("k", "v")
        q.collect()                    # warm outside the timed lap
        ev0, f0 = _counters("alerts.evaluations", "alerts.fired")

        def client():
            for _ in range(5):
                q.collect()
                sampler.tick()         # the hook evaluates every rule

        threads = [threading.Thread(target=client) for _ in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        sampler.tick()

        ev, f = _counters("alerts.evaluations", "alerts.fired")
        assert ev - ev0 > 0            # the plane was LIVE, not asleep
        assert f - f0 == 0             # and a clean lap fired nothing
        assert manager.active_count() == 0
        assert manager.digest()["active"] == 0
    finally:
        alerts.reset_manager()
        timeseries.reset_sampler()
        sess.close()
