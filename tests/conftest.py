"""Test bootstrap.

Distribution is tested the way the reference tests it — a real local
multi-way runtime in one process (`local[4]` SparkSession in
`SparkInvolvedSuite.scala:29-35`): here, an 8-device virtual CPU mesh via
`parallel.virtual.ensure_devices` (jax_num_cpu_devices), forced before
any test touches a device.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# The environment's site hook pins jax_platforms to the axon TPU plugin,
# overriding JAX_PLATFORMS; force the virtual 8-device CPU mesh for tests.
jax.config.update("jax_platforms", "cpu")

from hyperspace_tpu.parallel.virtual import ensure_devices

ensure_devices(8)

import numpy as np
import pytest

from hyperspace_tpu.config import HyperspaceConf


@pytest.fixture
def conf(tmp_path):
    """A HyperspaceConf rooted in a fresh tmp warehouse."""
    return HyperspaceConf({
        "spark.hyperspace.warehouse.dir": str(tmp_path / "warehouse"),
    })


@pytest.fixture
def leak_sentinel():
    """Device-array leak sentinel, reusable by any suite: asserts the
    `jax.live_arrays()` count is unchanged across the enclosed block.
    Warm the caches FIRST (run the workload once before entering), then
    wrap the repeat runs — a steady state that still accretes arrays is
    a leak (e.g. a cache retaining buffers for dead host sources).

        with leak_sentinel():
            for _ in range(3):
                df.collect()

    `tolerance` forgives a bounded number of new arrays (jit constants
    materialized lazily on first post-warm dispatch)."""
    import gc
    from contextlib import contextmanager

    @contextmanager
    def sentinel(tolerance: int = 0):
        gc.collect()
        before = len(jax.live_arrays())
        yield
        gc.collect()
        after = len(jax.live_arrays())
        assert after - before <= tolerance, (
            f"device-array leak: {after - before} new live arrays "
            f"(tolerance {tolerance}; {before} -> {after})")

    return sentinel


@pytest.fixture
def fault_injector():
    """Arm the plan-driven fault injector at the storage seam and the
    Action phase boundaries, with guaranteed uninstall:

        inj = fault_injector(FaultRule("action.CreateAction.op",
                                       kind="crash"))
        with pytest.raises(InjectedCrash):
            hs.create_index(df, cfg)
        assert inj.fired("action.*") == 1

    Calling the fixture again replaces the active plan."""
    from hyperspace_tpu.utils import faults

    def arm(*rules, seed: int = 0) -> faults.FaultInjector:
        return faults.install(faults.FaultInjector(rules, seed=seed))

    yield arm
    from hyperspace_tpu.utils import faults as _faults
    _faults.uninstall()


@pytest.fixture
def sample_parquet(tmp_path):
    """Deterministic sample dataset written to parquet (parity with the
    reference's `SampleData` fixture, `SampleData.scala:22-34`)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(42)
    n = 1000
    table = pa.table({
        "id": np.arange(n, dtype=np.int64),
        "clicks": rng.integers(0, 100, n).astype(np.int32),
        "score": rng.random(n).astype(np.float64),
        "imprs": rng.integers(0, 10, n).astype(np.int64),
        "query": pa.array([f"q{int(v)}" for v in rng.integers(0, 50, n)]),
    })
    path = tmp_path / "sample_data"
    path.mkdir(parents=True, exist_ok=True)
    pq.write_table(table, str(path / "part-0.parquet"))
    return str(path)
