"""Test bootstrap.

Distribution is tested the way the reference tests it — a real local
multi-way runtime in one process (`local[4]` SparkSession in
`SparkInvolvedSuite.scala:29-35`): here, an 8-device virtual CPU mesh via
XLA's host-platform device-count flag. Env vars must be set before jax is
first imported.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# The environment's site hook pins jax_platforms to the axon TPU plugin,
# overriding JAX_PLATFORMS; force the virtual 8-device CPU mesh for tests.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from hyperspace_tpu.config import HyperspaceConf


@pytest.fixture
def conf(tmp_path):
    """A HyperspaceConf rooted in a fresh tmp warehouse."""
    return HyperspaceConf({
        "spark.hyperspace.warehouse.dir": str(tmp_path / "warehouse"),
    })


@pytest.fixture
def sample_parquet(tmp_path):
    """Deterministic sample dataset written to parquet (parity with the
    reference's `SampleData` fixture, `SampleData.scala:22-34`)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(42)
    n = 1000
    table = pa.table({
        "id": np.arange(n, dtype=np.int64),
        "clicks": rng.integers(0, 100, n).astype(np.int32),
        "score": rng.random(n).astype(np.float64),
        "imprs": rng.integers(0, 10, n).astype(np.int64),
        "query": pa.array([f"q{int(v)}" for v in rng.integers(0, 50, n)]),
    })
    path = tmp_path / "sample_data"
    path.mkdir(parents=True, exist_ok=True)
    pq.write_table(table, str(path / "part-0.parquet"))
    return str(path)
