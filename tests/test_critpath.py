"""Critical-path extraction (telemetry/critical_path.py): the
closed-set decomposition and its sum-exactness contract (the residual
makes the sum exact BY CONSTRUCTION), counter publication, span
classification, flight-ring + sampler integration, and sum-exactness
under concurrent stamping."""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import telemetry
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.telemetry import critical_path, flight, timeseries
from hyperspace_tpu.telemetry.critical_path import (SEGMENT_SOURCES,
                                                    SEGMENTS,
                                                    SUM_EXACT_EPSILON_S)


def _counter(name):
    return telemetry.get_registry().counters_dict().get(name, 0)


def _finished_metrics(seconds_by_source=None, tag="q", busy_s=0.0):
    """A finished QueryMetrics with chosen per-query second counters;
    `busy_s` gives the query real wall so attributed segments fit
    under it (a zero-wall query overlaps by construction)."""
    qm = telemetry.QueryMetrics(description=tag)
    for source, s in (seconds_by_source or {}).items():
        qm.add_seconds(source, s)
    if busy_s:
        time.sleep(busy_s)
    qm.finish()
    return qm


# ---------------------------------------------------------------------------
# The decomposition + the sum contract
# ---------------------------------------------------------------------------


def test_decompose_closed_set_and_sum_exact():
    qm = _finished_metrics({
        "serve.queue_wait_s": 0.010,
        "compile.seconds": 0.005,
        "device.dispatch_s": 0.002,
        "link.h2d_s": 0.001,
    }, busy_s=0.025)
    cp = critical_path.decompose(qm)
    assert set(cp["segments"]) == set(SEGMENTS)
    assert abs(cp["sum_s"] - cp["wall_s"]) <= SUM_EXACT_EPSILON_S
    # the residual is exactly wall minus the attributed segments
    attributed = sum(v for k, v in cp["segments"].items()
                     if k != "host_python")
    assert cp["segments"]["host_python"] == \
        pytest.approx(cp["wall_s"] - attributed, abs=2e-6)
    assert cp["overlap_s"] == 0.0


def test_decompose_unfinished_is_none():
    qm = telemetry.QueryMetrics(description="unfinished")
    assert critical_path.decompose(qm) is None
    assert critical_path.stamp(qm) is None


def test_dominant_segment_named():
    qm = _finished_metrics({"compile.seconds": 30.0})
    cp = critical_path.decompose(qm)
    assert cp["dominant"] == "compile"
    # a bare query's wall is all host orchestration
    cp2 = critical_path.decompose(_finished_metrics())
    assert cp2["dominant"] == "host_python"


def test_overlap_reported_not_clamped_silently():
    """Pool threads can attribute more seconds than the wall; the
    negative residual and the positive overlap both say so, and the
    sum STAYS exact (the signed residual is the contract)."""
    qm = _finished_metrics({"link.h2d_s": 5.0, "link.d2h_s": 5.0})
    cp = critical_path.decompose(qm)
    assert cp["segments"]["host_python"] < 0
    assert cp["overlap_s"] == pytest.approx(10.0 - cp["wall_s"],
                                            abs=1e-5)
    assert cp["segments"]["host_python"] == pytest.approx(
        cp["wall_s"] - 10.0, abs=1e-5)
    assert abs(cp["sum_s"] - cp["wall_s"]) <= SUM_EXACT_EPSILON_S


def test_negative_source_counter_clamped():
    qm = _finished_metrics({"serve.queue_wait_s": -1.0})
    cp = critical_path.decompose(qm)
    assert cp["segments"]["queue_wait"] == 0.0


# ---------------------------------------------------------------------------
# stamp(): attachment + counter publication
# ---------------------------------------------------------------------------


def test_stamp_attaches_and_rides_to_dict():
    qm = _finished_metrics({"compile.seconds": 0.004})
    cp = critical_path.stamp(qm, publish=False)
    assert qm.critical_path is cp
    assert qm.to_dict()["critical_path"]["dominant"] == cp["dominant"]
    assert qm.summary()["critical_path"]["wall_s"] == cp["wall_s"]


def test_stamp_publishes_monotonic_counters():
    before_wall = _counter("critpath.wall.seconds")
    before_q = _counter("critpath.queries")
    before_compile = _counter("critpath.compile.seconds")
    before_overlap = _counter("critpath.overlap.seconds")

    qm = _finished_metrics({"compile.seconds": 0.25})
    critical_path.stamp(qm)
    assert _counter("critpath.queries") == before_q + 1
    assert _counter("critpath.wall.seconds") == \
        pytest.approx(before_wall + qm.critical_path["wall_s"], abs=1e-6)
    assert _counter("critpath.compile.seconds") == \
        pytest.approx(before_compile + 0.25, abs=1e-3)

    # an overlapping query publishes overlap and never DECREMENTS a
    # segment counter for its negative residual
    over = _finished_metrics({"link.h2d_s": 2.0})
    critical_path.stamp(over)
    assert over.critical_path["segments"]["host_python"] < 0
    assert _counter("critpath.overlap.seconds") > before_overlap
    assert _counter("critpath.host_python.seconds") >= 0


def test_sum_exact_under_concurrent_stamping():
    """N threads stamping interleaved: every stamped decomposition is
    individually sum-exact and the process counters account for every
    wall exactly once."""
    before_q = _counter("critpath.queries")
    before_wall = _counter("critpath.wall.seconds")
    rng = np.random.default_rng(7)
    sources = list(SEGMENT_SOURCES.values())
    stamped = []
    lock = threading.Lock()

    def worker(seed):
        r = np.random.default_rng(seed)
        for _ in range(25):
            chosen = {s: float(r.random() * 1e-3)
                      for s in r.choice(sources, size=3, replace=False)}
            qm = _finished_metrics(chosen)
            critical_path.stamp(qm)
            with lock:
                stamped.append(qm)

    threads = [threading.Thread(target=worker, args=(int(s),))
               for s in rng.integers(0, 1 << 31, size=6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert len(stamped) == 150
    for qm in stamped:
        cp = qm.critical_path
        assert abs(cp["sum_s"] - cp["wall_s"]) <= SUM_EXACT_EPSILON_S
    assert _counter("critpath.queries") == before_q + 150
    walls = sum(q.critical_path["wall_s"] for q in stamped)
    assert _counter("critpath.wall.seconds") == \
        pytest.approx(before_wall + walls, rel=1e-6)


# ---------------------------------------------------------------------------
# Span classification (the timeline view)
# ---------------------------------------------------------------------------


def test_span_classification_closed_set():
    cases = [
        (("compile", "jit_lower"), "compile"),
        (("compile.aot", "warmup"), "compile"),
        (("link", "h2d_chunk"), "link_h2d"),
        (("link", "d2h_fetch"), "link_d2h"),
        (("cache", "fill"), "cache_fill_wait"),
        (("serve.batch", "gather"), "batch_window"),
        (("plan", "rewrite"), None),       # host work by definition
        (("serving", "admit"), None),      # no prefix-confusion
    ]
    for (cat, name), want in cases:
        assert critical_path._classify_span(cat, name) == want, (cat,
                                                                 name)


def test_span_timeline_none_without_tracer():
    from hyperspace_tpu.telemetry import trace
    assert trace.tracer() is None  # the suite's always-off default
    assert critical_path.span_timeline(_finished_metrics()) is None


# ---------------------------------------------------------------------------
# Engine integration: the scheduler stamps, the ring and sampler carry
# ---------------------------------------------------------------------------


@pytest.fixture
def small_env(tmp_path):
    rng = np.random.default_rng(3)
    n = 4000
    data = tmp_path / "data"
    data.mkdir()
    pq.write_table(pa.table({
        "a": rng.integers(0, 100, n).astype(np.int64),
        "v": rng.random(n).astype(np.float64),
    }), str(data / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
    }))
    return sess, str(data)


def test_collect_stamps_flight_ring_entries(small_env):
    sess, data = small_env
    seq0 = flight.get_recorder().last_seq
    df = sess.read_parquet(data).filter(col("a") > lit(50))
    df.collect()
    df.collect()
    fresh, _last = flight.get_recorder().snapshot(seq0)
    stamped = [m for m in fresh
               if getattr(m, "critical_path", None) is not None]
    assert len(stamped) >= 2
    for qm in stamped:
        cp = qm.critical_path
        assert set(cp["segments"]) == set(SEGMENTS)
        assert abs(cp["sum_s"] - cp["wall_s"]) <= SUM_EXACT_EPSILON_S
        # wall includes the queue wait: no segment exceeds the wall
        # unless overlap says so
        if cp["overlap_s"] == 0.0:
            assert max(cp["segments"].values()) <= cp["wall_s"] + 1e-6


def test_critpath_disabled_by_conf(tmp_path, small_env):
    _sess, data = small_env
    off = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh2"),
        "spark.hyperspace.telemetry.critpath.enabled": "false",
    }))
    seq0 = flight.get_recorder().last_seq
    off.read_parquet(data).filter(col("a") > lit(50)).collect()
    fresh, _last = flight.get_recorder().snapshot(seq0)
    assert fresh and all(getattr(m, "critical_path", None) is None
                         for m in fresh)


def test_window_shares_from_sampler(small_env):
    sess, data = small_env
    sampler = timeseries.get_sampler()
    sampler.tick()
    t0 = time.time()
    df = sess.read_parquet(data).filter(col("a") > lit(50))
    for _ in range(3):
        df.collect()
    sampler.tick()
    shares = critical_path.window_shares(since_t=t0)
    assert shares["queries_per_s"] > 0
    assert shares["dominant"] in SEGMENTS
    # shares cover the wall to within rounding + reported overlap
    total = sum(shares["shares"].values())
    assert total == pytest.approx(1.0 + shares["overlap"], abs=0.02)
    # and the windowed gauges were published for scrapers
    gauges = telemetry.get_registry().series_snapshot()["gauges"]
    assert f"window.critpath.{shares['dominant']}.share" in gauges


def test_window_shares_empty_window_renders_shape():
    sampler = timeseries.get_sampler()
    sampler.tick()
    out = critical_path.window_shares(since_t=time.time() + 60)
    assert out["queries_per_s"] == 0.0
    assert set(out["shares"]) == set(SEGMENTS)
    assert out["dominant"] is None
