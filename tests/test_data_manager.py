"""Versioned data dirs + path resolver tests."""

import os

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.index.data_manager import IndexDataManagerImpl
from hyperspace_tpu.index.path_resolver import PathResolver


def test_version_scan(tmp_path):
    root = str(tmp_path / "idx")
    mgr = IndexDataManagerImpl(root)
    assert mgr.get_latest_version_id() is None
    os.makedirs(os.path.join(root, "v__=0"))
    os.makedirs(os.path.join(root, "v__=3"))
    os.makedirs(os.path.join(root, "_hyperspace_log"))
    os.makedirs(os.path.join(root, "v__=bogus"))
    mgr.commit(0)
    mgr.commit(3)
    assert mgr.get_latest_version_id() == 3
    assert mgr.get_path(4) == os.path.join(root, "v__=4")


def test_uncommitted_version_invisible_to_readers(tmp_path):
    """A `v__=N` dir without the `_committed` marker (a crashed build's
    partial write) must never be SERVED — but the next build must skip
    its number and vacuum must still hard-delete it."""
    root = str(tmp_path / "idx")
    mgr = IndexDataManagerImpl(root)
    os.makedirs(os.path.join(root, "v__=0"))
    mgr.commit(0)
    os.makedirs(os.path.join(root, "v__=1"))  # partial: no marker
    assert mgr.get_latest_version_id() == 0
    assert mgr.all_version_ids() == [0, 1]
    assert mgr.next_version_id() == 2
    assert mgr.is_committed(0) and not mgr.is_committed(1)
    mgr.commit(1)
    assert mgr.get_latest_version_id() == 1


def test_delete_version(tmp_path):
    root = str(tmp_path / "idx")
    mgr = IndexDataManagerImpl(root)
    os.makedirs(os.path.join(root, "v__=0"))
    mgr.delete(0)
    assert not os.path.exists(os.path.join(root, "v__=0"))


def test_path_resolver_defaults(tmp_path):
    conf = HyperspaceConf({"hyperspace.warehouse.dir": str(tmp_path / "wh")})
    resolver = PathResolver(conf)
    assert resolver.system_path == str(tmp_path / "wh" / "indexes")
    assert resolver.get_index_path("My Index") == str(
        tmp_path / "wh" / "indexes" / "My_Index")


def test_path_resolver_case_insensitive_match(tmp_path):
    conf = HyperspaceConf(
        {"spark.hyperspace.system.path": str(tmp_path / "sys")})
    os.makedirs(str(tmp_path / "sys" / "MyIndex"))
    resolver = PathResolver(conf)
    assert resolver.get_index_path("myindex") == str(tmp_path / "sys" / "MyIndex")


def test_conf_key_aliasing(tmp_path):
    conf = HyperspaceConf()
    conf.set("hyperspace.index.num.buckets", 16)
    assert conf.num_buckets == 16
    assert conf.get("spark.hyperspace.index.num.buckets") == "16"
    assert HyperspaceConf().num_buckets == 200


def test_catalog_skips_corrupt_index(tmp_path):
    """One unreadable index must not take down the whole catalog listing."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fakes import make_entry
    from hyperspace_tpu.index.log_manager import IndexLogManagerImpl
    from hyperspace_tpu.index.manager import IndexCollectionManager

    conf = HyperspaceConf(
        {"spark.hyperspace.system.path": str(tmp_path / "sys")})
    good = IndexLogManagerImpl(str(tmp_path / "sys" / "good"))
    good.write_log(0, make_entry(name="good", state="ACTIVE"))
    bad_dir = tmp_path / "sys" / "bad" / "_hyperspace_log"
    bad_dir.mkdir(parents=True)
    (bad_dir / "0").write_text("{torn")
    mgr = IndexCollectionManager(conf)
    names = [s.name for s in mgr.indexes()]
    assert names == ["good"]
