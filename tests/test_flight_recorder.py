"""Query flight recorder (telemetry/flight.py): ring bounds, the
slow-query dump round trip (dump -> reload -> diff against a live
tree), thread safety of concurrent collects, and the engine wiring
(every session-attached collect lands in the ring)."""

import json
import os
import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import telemetry
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.telemetry import diff, flight


def _finished_metrics(tag, wall_op=0.0):
    qm = telemetry.QueryMetrics(description=tag)
    op = qm.start_operator("Scan")
    qm.finish_operator(op, rows_out=5)
    qm.finish()
    return qm


@pytest.fixture
def sales_env(tmp_path):
    rng = np.random.default_rng(3)
    n = 2000
    data_dir = tmp_path / "sales"
    data_dir.mkdir()
    pq.write_table(pa.table({
        "key": rng.integers(0, 50, n).astype(np.int64),
        "qty": rng.integers(1, 10, n).astype(np.int64),
    }), str(data_dir / "part-0.parquet"))

    def session(**extra):
        conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh")}
        conf.update(extra)
        return HyperspaceSession(HyperspaceConf(conf))

    return session, str(data_dir)


# ---------------------------------------------------------------------------
# Ring semantics
# ---------------------------------------------------------------------------


def test_ring_is_bounded_and_keeps_newest():
    rec = flight.FlightRecorder(capacity=4)
    for i in range(10):
        rec.record(_finished_metrics(f"q{i}"))
    assert len(rec) == 4
    assert [m.description for m in rec.queries()] == \
        ["q6", "q7", "q8", "q9"]
    assert [m.description for m in rec.queries(2)] == ["q8", "q9"]
    rec.clear()
    assert len(rec) == 0


def test_collect_feeds_the_process_ring(sales_env):
    session, data_dir = sales_env
    sess = session()
    rec = sess.flight_recorder()
    assert rec is telemetry.get_recorder()
    before = len(rec.queries())
    df = sess.read_parquet(data_dir).filter(col("qty") > lit(5)) \
        .select("key")
    df.collect()
    df.collect()
    queries = rec.queries()
    assert len(queries) >= min(before + 2, rec.capacity)
    # the ring holds the SAME recorder objects the session surfaced
    assert queries[-1] is sess.last_query_metrics()
    assert queries[-1].wall_s is not None  # only finished recorders


# ---------------------------------------------------------------------------
# Slow-query dump
# ---------------------------------------------------------------------------


def test_slow_dump_round_trip_and_diff(sales_env, tmp_path):
    session, data_dir = sales_env
    dump_dir = str(tmp_path / "slowlog")
    sess = session(**{
        "spark.hyperspace.telemetry.slowlog.seconds": "0.000001",
        "spark.hyperspace.telemetry.slowlog.dir": dump_dir})
    df = sess.read_parquet(data_dir).filter(col("qty") > lit(5)) \
        .select("key")
    df.collect()
    flight.get_recorder().drain()  # dumps ride a background lane now
    dumps = [f for f in os.listdir(dump_dir) if f.endswith(".json")]
    assert len(dumps) == 1
    path = os.path.join(dump_dir, dumps[0])

    doc = flight.load_dump(path)
    assert doc["kind"] == "hyperspace-slowlog"
    assert doc["wall_s"] == pytest.approx(
        sess.last_query_metrics().wall_s)
    assert doc["threshold_s"] == pytest.approx(1e-6)
    # the dump carries the FULL metric tree + a registry snapshot
    assert doc["metrics"]["operators"]
    assert "counters" in doc["registry"]
    live = sess.last_query_metrics().to_dict()
    assert doc["metrics"]["operators"] == live["operators"]

    # round trip: reload the dump and diff it against a live re-run of
    # the same query — the post-hoc diagnosis workflow, no re-tracing
    df.collect()
    qd = diff.diff_trees(doc["metrics"],
                         sess.last_query_metrics().to_dict(),
                         name="slow-vs-rerun")
    assert qd.old_wall is not None and qd.new_wall is not None
    assert {b.name for b in qd.buckets} >= {"compute", "link",
                                            "compile", "residual"}
    total = sum(b.seconds for b in qd.buckets)
    assert total == pytest.approx(qd.delta, abs=1e-6)


def test_slow_dump_respects_threshold(sales_env, tmp_path):
    session, data_dir = sales_env
    dump_dir = str(tmp_path / "slowlog")
    # a threshold no test query reaches: ring records, nothing dumps
    sess = session(**{
        "spark.hyperspace.telemetry.slowlog.seconds": "3600",
        "spark.hyperspace.telemetry.slowlog.dir": dump_dir})
    sess.read_parquet(data_dir).select("key").collect()
    assert not os.path.exists(dump_dir)
    # default (0) disables dumping entirely
    sess2 = session()
    assert sess2.conf.slowlog_seconds == 0.0
    sess2.read_parquet(data_dir).select("key").collect()
    assert not os.path.exists(sess2.conf.slowlog_dir)


def test_slow_dump_prunes_to_keep(tmp_path):
    conf = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "spark.hyperspace.telemetry.slowlog.seconds": "0.000001",
        "spark.hyperspace.telemetry.slowlog.keep": "2"})
    rec = flight.FlightRecorder(capacity=8)
    paths = [rec.record(_finished_metrics(f"q{i}"), conf=conf)
             for i in range(5)]
    rec.drain()  # dump writes are queued; flush before inspecting
    assert all(paths)
    dumps = sorted(f for f in os.listdir(conf.slowlog_dir)
                   if f.endswith(".json"))
    assert len(dumps) == 2
    # the newest dumps survive the prune
    assert os.path.basename(paths[-1]) in dumps


def test_dump_failure_never_fails_the_query(sales_env, tmp_path):
    session, data_dir = sales_env
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("a file where the dump dir must go")
    sess = session(**{
        "spark.hyperspace.telemetry.slowlog.seconds": "0.000001",
        "spark.hyperspace.telemetry.slowlog.dir":
            str(blocker / "slowlog")})
    errors_before = telemetry.get_registry() \
        .counter("flight.dump_errors").value
    table = sess.read_parquet(data_dir).select("key").collect()
    assert table.num_rows > 0  # the query succeeded regardless
    flight.get_recorder().drain()  # failure lands on the dump lane
    assert telemetry.get_registry().counter("flight.dump_errors") \
        .value == errors_before + 1


def test_load_dump_rejects_non_dumps(tmp_path):
    p = tmp_path / "x.json"
    p.write_text(json.dumps({"metric": "m"}))
    with pytest.raises(ValueError):
        flight.load_dump(str(p))


# ---------------------------------------------------------------------------
# Thread safety
# ---------------------------------------------------------------------------


def test_snapshot_incremental_cursor():
    """`snapshot(since_seq)` is the miner's incremental poll: each call
    returns only entries newer than the cursor, the cursor survives
    ring rotation (a slow consumer skips, never stalls), and clear()
    keeps sequence monotonicity."""
    rec = flight.FlightRecorder(capacity=4)
    for i in range(3):
        rec.record(_finished_metrics(f"q{i}"))
    fresh, cursor = rec.snapshot(0)
    assert [m.description for m in fresh] == ["q0", "q1", "q2"]
    assert cursor == rec.last_seq
    # Nothing new: empty, same cursor.
    again, cursor2 = rec.snapshot(cursor)
    assert again == [] and cursor2 == cursor
    # More entries than capacity arrive between polls: the consumer
    # gets what survived, and the cursor jumps past the rotated-out.
    for i in range(3, 10):
        rec.record(_finished_metrics(f"q{i}"))
    fresh, cursor3 = rec.snapshot(cursor)
    assert [m.description for m in fresh] == ["q6", "q7", "q8", "q9"]
    assert cursor3 == cursor + 7
    # Sequence ids are stamped on the metrics and strictly increasing.
    seqs = [m.flight_seq for m in rec.queries()]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    rec.clear()
    rec.record(_finished_metrics("post-clear"))
    fresh, cursor4 = rec.snapshot(cursor3)
    assert [m.description for m in fresh] == ["post-clear"]
    assert cursor4 == cursor3 + 1


def test_concurrent_record_is_safe():
    rec = flight.FlightRecorder(capacity=32)
    n_threads, per_thread = 8, 50
    errors = []

    def worker(t):
        try:
            for i in range(per_thread):
                rec.record(_finished_metrics(f"t{t}-{i}"))
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    assert len(rec) == 32  # full, exactly at capacity
    assert all(m.wall_s is not None for m in rec.queries())


def test_concurrent_collects_append_to_ring(sales_env):
    """Concurrent session-attached collects (each with its own
    recorder — the contextvar scoping) all land in the shared ring
    without corrupting it."""
    session, data_dir = sales_env
    rec = telemetry.get_recorder()
    rec.clear()
    n_threads = 6
    errors = []
    barrier = threading.Barrier(n_threads)

    def worker(i):
        try:
            sess = session()
            df = sess.read_parquet(data_dir) \
                .filter(col("qty") > lit(i % 9)).select("key")
            barrier.wait(timeout=30)
            df.collect()
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    queries = rec.queries()
    assert len(queries) >= n_threads
    # every recorder in the ring is finished and distinct
    tail = queries[-n_threads:]
    assert len({id(m) for m in tail}) == n_threads
    assert all(m.wall_s is not None for m in tail)
