"""Distribution tests on the virtual 8-device CPU mesh (conftest calls
`parallel.virtual.ensure_devices(8)`) — the reference's `local[4]`
equivalent (SURVEY §4 takeaway)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from hyperspace_tpu.io import columnar
from hyperspace_tpu.parallel import spmd
from hyperspace_tpu.parallel.build import distributed_build
from hyperspace_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    import jax
    assert len(jax.devices()) >= 8, "virtual device mesh missing"
    return make_mesh(8)


def make_batch(n, seed=0, with_strings=True):
    rng = np.random.default_rng(seed)
    cols = {
        "k": rng.integers(0, max(4, n // 8), n).astype(np.int64),
        "v": rng.random(n).astype(np.float64),
    }
    if with_strings:
        cols["s"] = pa.array([f"name{int(x):03d}"
                              for x in rng.integers(0, 50, n)])
    return columnar.from_arrow(pa.table(cols))


def test_distributed_build_matches_single_chip(mesh):
    """The all_to_all build must produce the same bucket contents as the
    single-device pipeline."""
    from hyperspace_tpu.ops.build import build_sorted

    batch = make_batch(1000, seed=3)
    built, lengths = distributed_build(batch, ["k"], 16, mesh)
    assert built.num_rows == 1000
    assert int(lengths.sum()) == 1000

    single, starts, ends = build_sorted(batch, ["k"], 16)
    single_lengths = np.asarray(ends) - np.asarray(starts)
    assert (lengths == single_lengths).all()

    # identical rows per bucket (as multisets)
    dist_df = columnar.to_arrow(built).to_pandas()
    single_df = columnar.to_arrow(single).to_pandas()
    db = np.repeat(np.arange(16), lengths)
    sb = np.repeat(np.arange(16), single_lengths)
    dist_df["b"] = db
    single_df["b"] = sb
    cols = ["b", "k", "v", "s"]
    a = dist_df[cols].sort_values(cols).reset_index(drop=True)
    b = single_df[cols].sort_values(cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b)


def test_distributed_build_sorted_within_buckets(mesh):
    batch = make_batch(500, seed=4, with_strings=False)
    built, lengths = distributed_build(batch, ["k"], 8, mesh)
    k = np.asarray(built.column("k").data)
    start = 0
    for b in range(8):
        seg = k[start:start + lengths[b]]
        assert (np.diff(seg) >= 0).all(), f"bucket {b} not sorted"
        start += lengths[b]


def test_distributed_build_capacity_overflow_retry(mesh):
    """Skewed keys (all rows -> one bucket) overflow the default capacity;
    the exact-retry path must still deliver every row."""
    n = 800
    batch = columnar.from_arrow(pa.table({
        "k": np.full(n, 7, dtype=np.int64),
        "v": np.arange(n, dtype=np.float64),
    }))
    built, lengths = distributed_build(batch, ["k"], 16, mesh,
                                       capacity_factor=0.5)
    assert built.num_rows == n
    assert int(lengths.sum()) == n
    assert int(lengths.max()) == n  # all in one bucket


def _sharded_pair(mesh, left, right, buckets=16):
    lb, ll = distributed_build(left, ["k"], buckets, mesh)
    rb, rl = distributed_build(right, ["k"], buckets, mesh)
    return (spmd.shard_bucket_ordered(lb, ll, mesh),
            spmd.shard_bucket_ordered(rb, rl, mesh), lb, rb)


def test_spmd_join_matches_pandas(mesh):
    left = make_batch(600, seed=5, with_strings=False)
    right = make_batch(300, seed=6, with_strings=False)
    lsh, rsh, lb, rb = _sharded_pair(mesh, left, right)
    li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"])
    lk = np.asarray(lsh.batch.column("k").data)[np.asarray(li)]
    rk = np.asarray(rsh.batch.column("k").data)[np.asarray(ri)]
    assert (lk == rk).all()
    ref = pd.DataFrame({"k": np.asarray(lb.column("k").data)}).merge(
        pd.DataFrame({"k": np.asarray(rb.column("k").data)}), on="k")
    assert len(ref) == len(np.asarray(li))


def test_spmd_full_outer_matches_pandas(mesh):
    left = make_batch(500, seed=8, with_strings=False)
    right = make_batch(260, seed=9, with_strings=False)
    lsh, rsh, lb, rb = _sharded_pair(mesh, left, right)
    li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"],
                                       how="full_outer")
    li, ri = np.asarray(li), np.asarray(ri)
    lk_p = np.asarray(lsh.batch.column("k").data)
    rk_p = np.asarray(rsh.batch.column("k").data)
    got = pd.DataFrame({
        "lk": np.where(li >= 0, lk_p[np.clip(li, 0, None)], -1),
        "rk": np.where(ri >= 0, rk_p[np.clip(ri, 0, None)], -1)})
    lpd = pd.DataFrame({"lk": np.asarray(lb.column("k").data)})
    rpd = pd.DataFrame({"rk": np.asarray(rb.column("k").data)})
    exp = lpd.assign(j=lpd.lk).merge(rpd.assign(j=rpd.rk), on="j",
                                     how="outer").drop(columns="j")
    exp = exp.fillna(-1).astype(np.int64)
    key = ["lk", "rk"]
    pd.testing.assert_frame_equal(
        got.sort_values(key).reset_index(drop=True),
        exp[key].sort_values(key).reset_index(drop=True),
        check_dtype=False)


def test_spmd_semi_anti_matches_pandas(mesh):
    left = make_batch(500, seed=10, with_strings=False)
    right = make_batch(120, seed=11, with_strings=False)
    lsh, rsh, lb, rb = _sharded_pair(mesh, left, right)
    lk = np.asarray(lb.column("k").data)
    rset = set(np.asarray(rb.column("k").data))
    for anti in (False, True):
        li = spmd.sharded_semi_anti_indices(lsh, rsh, ["k"], ["k"],
                                            anti=anti)
        member = np.asarray([k in rset for k in lk])
        exp = int((~member if anti else member).sum())
        assert len(np.asarray(li)) == exp, f"anti={anti}"
        keys = np.asarray(lsh.batch.column("k").data)[np.asarray(li)]
        assert np.isin(keys, list(rset)).all() != anti or exp == 0


def test_spmd_join_hot_bucket_overflow_retry(mesh):
    """A hot key concentrating most rows in ONE bucket must still join
    exactly: the static-capacity expansion overflows and the doubling
    retry recovers every pair (nothing silently truncated)."""
    n = 1200
    hot = np.full(n - 100, 7, dtype=np.int64)
    rest = np.arange(100, dtype=np.int64) + 100
    left = columnar.from_arrow(pa.table({
        "k": np.concatenate([hot, rest]),
        "v": np.arange(n, dtype=np.float64)}))
    right = columnar.from_arrow(pa.table({
        "k": np.asarray([7, 7, 120, 150], dtype=np.int64),
        "w": np.arange(4, dtype=np.float64)}))
    lsh, rsh, lb, rb = _sharded_pair(mesh, left, right)
    spmd._CAP_MEMO.clear()
    li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"],
                                       capacity_factor=0.01)
    lk = np.asarray(lsh.batch.column("k").data)[np.asarray(li)]
    rk = np.asarray(rsh.batch.column("k").data)[np.asarray(ri)]
    assert (lk == rk).all()
    # hot key expands (n-100)*2; the two singles match once each
    assert len(np.asarray(li)) == (n - 100) * 2 + 2
    spmd._CAP_MEMO.clear()


def test_spmd_join_memory_is_sharded(mesh):
    """The born-sharded [S*C] layout must give every device ~1/S of the
    rows — assert the actual per-shard bytes of the resident columns."""
    left = make_batch(4000, seed=12, with_strings=False)
    right = make_batch(2000, seed=13, with_strings=False)
    lsh, _rsh, _lb, _rb = _sharded_pair(mesh, left, right)
    for name in ("k", "v"):
        arr = lsh.batch.column(name).data
        shards = arr.addressable_shards
        assert len(shards) == 8
        per_dev = max(s.data.nbytes for s in shards)
        assert per_dev <= arr.nbytes / 8 + 1024, (
            f"device holds {per_dev}B of a {arr.nbytes}B array — "
            "not sharded")
    # and the padded layout is tight: cells within 2x of true rows
    assert 8 * lsh.rows_per_shard <= 2 * left.num_rows + 8 * 16


def test_spmd_left_semi_empty_right(mesh):
    """Degenerate sides stay off the mesh at the ENGINE level
    (`ScanExec._execute_sharded` returns None for zero rows); at the
    spmd API level an all-padding right side must still answer
    membership correctly."""
    left = make_batch(300, seed=14, with_strings=False)
    empty_rows = columnar.from_arrow(pa.table({
        "k": np.zeros(1, dtype=np.int64), "v": np.zeros(1)}))
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    eb, el = distributed_build(empty_rows, ["k"], 16, mesh)
    lsh = spmd.shard_bucket_ordered(lb, ll, mesh)
    esh = spmd.shard_bucket_ordered(eb, el, mesh)
    anti = spmd.sharded_semi_anti_indices(lsh, esh, ["k"], ["k"],
                                          anti=True)
    lk = np.asarray(lb.column("k").data)
    assert len(np.asarray(anti)) == int((lk != 0).sum())


def test_repartition_sharded_mismatched_counts(mesh):
    """The ranker's fallback, post-deletion form: a device-resident
    batch re-buckets to a new count entirely in-program
    (`repartition_sharded`), and a join over the result matches the
    co-bucketed layout."""
    batch = make_batch(400, seed=7, with_strings=False)
    sh = spmd.repartition_sharded(batch, ["k"], 32, mesh)
    assert sh.num_buckets == 32
    assert sh.num_rows == 400


def test_graft_entry():
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    out = fn(*args)
    assert out[0].shape[0] == 4096
    __graft_entry__.dryrun_multichip(8)


def test_distributed_group_aggregate_matches_single_chip(mesh):
    """SPMD partial aggregation + host combine must equal the single-chip
    aggregation for every combinable function, incl. stddev over
    large-offset values (exact variance decomposition) and null inputs."""
    import pandas as pd
    import pyarrow as pa

    from hyperspace_tpu.io.columnar import from_arrow, to_arrow
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.parallel.aggregate import distributed_group_aggregate
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    rng = np.random.default_rng(31)
    n = 20_000
    table = pa.table({
        "g": rng.integers(0, 97, n).astype(np.int64),
        "h": pa.array([["a", "b", "c"][i % 3] for i in range(n)]),
        "x": pa.array([None if i % 13 == 0 else 1.7e6 + float(v)
                       for i, v in enumerate(rng.standard_normal(n))],
                      type=pa.float64()),
        "y": rng.integers(-1000, 1000, n).astype(np.int64),
    })
    schema = Schema.from_arrow(table.schema)
    specs = [AggSpec("count", "*", "cnt"), AggSpec("count", "x", "cx"),
             AggSpec("sum", "y", "sy"), AggSpec("avg", "x", "ax"),
             AggSpec("min", "y", "mny"), AggSpec("max", "y", "mxy"),
             AggSpec("stddev", "x", "sx")]
    out_schema = Aggregate(["g", "h"], specs,
                           Scan(["/nx"], schema)).schema
    batch = from_arrow(table)
    dist = distributed_group_aggregate(batch, ["g", "h"], specs,
                                       out_schema, mesh)
    single = group_aggregate(batch, ["g", "h"], specs, out_schema)

    d = (to_arrow(dist).to_pandas().sort_values(["g", "h"])
         .reset_index(drop=True))
    s = (to_arrow(single).to_pandas().sort_values(["g", "h"])
         .reset_index(drop=True))
    pd.testing.assert_frame_equal(d, s, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_distributed_aggregate_int64_exact(mesh):
    """int64 sum/min/max past 2^53 must stay exact under distribution
    (float64 accumulation would silently round)."""
    import pyarrow as pa

    from hyperspace_tpu.io.columnar import from_arrow, to_arrow
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.parallel.aggregate import distributed_group_aggregate
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    big = (1 << 53) + 1
    table = pa.table({"g": np.zeros(8, np.int64),
                      "y": np.array([big, big, big, big,
                                     big + 2, big + 2, big + 2, big + 2],
                                    dtype=np.int64)})
    schema = Schema.from_arrow(table.schema)
    specs = [AggSpec("sum", "y", "sy"), AggSpec("min", "y", "mny"),
             AggSpec("max", "y", "mxy")]
    out_schema = Aggregate(["g"], specs, Scan(["/nx"], schema)).schema
    batch = from_arrow(table)
    d = to_arrow(distributed_group_aggregate(batch, ["g"], specs,
                                             out_schema, mesh)).to_pandas()
    s = to_arrow(group_aggregate(batch, ["g"], specs,
                                 out_schema)).to_pandas()
    assert int(d.sy[0]) == int(s.sy[0]) == 8 * big + 8
    assert int(d.mny[0]) == big and int(d.mxy[0]) == big + 2


def test_spmd_left_outer_join_with_nulls(mesh):
    """SPMD left_outer: unmatched and null-key left rows emit right -1;
    matches equal pandas (null keys never match — Kleene)."""
    rng = np.random.default_rng(9)
    lk = rng.integers(0, 30, 400).astype(np.float64)
    lk[::17] = np.nan  # null keys via mask below
    lmask = ~np.isnan(lk)
    left = columnar.from_arrow(pa.table({
        "k": pa.array(np.where(lmask, lk, 0).astype(np.int64),
                      mask=~lmask),
        "x": rng.random(400)}))
    right = columnar.from_arrow(pa.table({
        "k": rng.integers(10, 50, 150).astype(np.int64),
        "y": rng.random(150)}))
    lsh, rsh, lb, rb = _sharded_pair(mesh, left, right)
    li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"],
                                       how="left_outer")
    li, ri = np.asarray(li), np.asarray(ri)
    lkey_p = np.asarray(lsh.batch.column("k").data)
    lval_p = (np.asarray(lsh.batch.column("k").validity)
              if lsh.batch.column("k").validity is not None
              else np.ones(len(lkey_p), bool))
    rkey_p = np.asarray(rsh.batch.column("k").data)
    # Matched pairs agree with pandas over the ORIGINAL layouts.
    lkey = np.asarray(lb.column("k").data)
    lval = (np.asarray(lb.column("k").validity)
            if lb.column("k").validity is not None
            else np.ones(len(lkey), bool))
    rkey = np.asarray(rb.column("k").data)
    lpd = pd.DataFrame({"k": lkey[lval]})
    rpd = pd.DataFrame({"k": rkey})
    matched = lpd.merge(rpd, on="k")
    got_matched = ri >= 0
    assert int(got_matched.sum()) == len(matched)
    assert (lkey_p[li[got_matched]] == rkey_p[ri[got_matched]]).all()
    assert lval_p[li[got_matched]].all()
    # every REAL left row appears; null/unmatched carry right -1 once
    assert len(li) == len(matched) + int((~lval).sum()) \
        + int((~np.isin(lkey, rkey) & lval).sum())


# -- two-axis (dcn x shard) mesh: multi-host topology ---------------------


@pytest.fixture(scope="module")
def mesh24():
    from hyperspace_tpu.parallel.mesh import make_mesh
    return make_mesh(8, dcn_size=2)


def test_two_axis_build_matches_single_chip(mesh24):
    from hyperspace_tpu.ops.build import build_sorted

    batch = make_batch(900, seed=21, with_strings=True)
    built, lengths = distributed_build(batch, ["k"], 16, mesh24)
    single, starts, ends = build_sorted(batch, ["k"], 16)
    sl = np.asarray(ends) - np.asarray(starts)
    assert (lengths == sl).all()
    cols = ["k", "v", "s"]
    a = columnar.to_arrow(built).to_pandas()[cols].sort_values(cols) \
        .reset_index(drop=True)
    b = columnar.to_arrow(single).to_pandas()[cols].sort_values(cols) \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b)


def test_two_axis_join_matches_pandas(mesh24):
    """Co-bucketed SPMD join over the 2-axis (dcn x shard) mesh —
    equal bucket counts need no in-program repartition, so the single
    program runs on multi-slice topologies too."""
    left = make_batch(700, seed=22, with_strings=False)
    right = make_batch(350, seed=23, with_strings=False)
    lb, ll = distributed_build(left, ["k"], 16, mesh24)
    rb, rl = distributed_build(right, ["k"], 16, mesh24)
    lsh = spmd.shard_bucket_ordered(lb, ll, mesh24)
    rsh = spmd.shard_bucket_ordered(rb, rl, mesh24)
    li, ri = spmd.sharded_join_indices(lsh, rsh, ["k"], ["k"])
    lk_p = np.asarray(lsh.batch.column("k").data)
    rk_p = np.asarray(rsh.batch.column("k").data)
    assert (lk_p[np.asarray(li)] == rk_p[np.asarray(ri)]).all()
    lk = np.asarray(lb.column("k").data)
    rk = np.asarray(rb.column("k").data)
    exp = pd.DataFrame({"k": lk}).merge(pd.DataFrame({"k": rk}), on="k")
    assert len(exp) == len(np.asarray(li))


def test_two_axis_collectives_confined_to_axes(mesh24):
    """SURVEY §2.12 "DCN only across slices": the build's heavy re-bucket
    all_to_all must be CONFINED to the inner (ICI) axis — replica groups
    {0..3},{4..7} — with only the slim cross-slice stage over DCN pairs
    {0,4},{1,5},... . Asserted on the COMPILED HLO's replica groups."""
    import re

    import jax.numpy as jnp

    from hyperspace_tpu.io.columnar import batch_to_tree
    from hyperspace_tpu.parallel.build import make_distributed_build_step

    batch = make_batch(1024, seed=24, with_strings=False)
    tree, _ = batch_to_tree(batch)
    in_tree = {name: dict(e, data=jnp.asarray(e["data"]))
               for name, e in tree.items()}
    in_tree["__valid__"] = jnp.ones(1024, dtype=bool)
    step = make_distributed_build_step(mesh24, ("k",), 16, 2.0)
    hlo = step.lower(in_tree).compile().as_text()
    groups = set(re.findall(r"replica_groups=(\{\{[0-9,{}]*\}\})", hlo))
    assert "{{0,1,2,3},{4,5,6,7}}" in groups, groups  # ICI stage
    assert "{{0,4},{1,5},{2,6},{3,7}}" in groups, groups  # DCN stage
    flat = "{{0,1,2,3,4,5,6,7}}"
    assert flat not in groups, "a collective spans the full mesh"
