"""Distribution tests on the virtual 8-device CPU mesh (conftest calls
`parallel.virtual.ensure_devices(8)`) — the reference's `local[4]`
equivalent (SURVEY §4 takeaway)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from hyperspace_tpu.io import columnar
from hyperspace_tpu.parallel.build import distributed_build
from hyperspace_tpu.parallel.join import (distributed_bucketed_join_indices,
                                          rebucket)
from hyperspace_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    import jax
    assert len(jax.devices()) >= 8, "virtual device mesh missing"
    return make_mesh(8)


def make_batch(n, seed=0, with_strings=True):
    rng = np.random.default_rng(seed)
    cols = {
        "k": rng.integers(0, max(4, n // 8), n).astype(np.int64),
        "v": rng.random(n).astype(np.float64),
    }
    if with_strings:
        cols["s"] = pa.array([f"name{int(x):03d}"
                              for x in rng.integers(0, 50, n)])
    return columnar.from_arrow(pa.table(cols))


def test_distributed_build_matches_single_chip(mesh):
    """The all_to_all build must produce the same bucket contents as the
    single-device pipeline."""
    from hyperspace_tpu.ops.build import build_sorted

    batch = make_batch(1000, seed=3)
    built, lengths = distributed_build(batch, ["k"], 16, mesh)
    assert built.num_rows == 1000
    assert int(lengths.sum()) == 1000

    single, starts, ends = build_sorted(batch, ["k"], 16)
    single_lengths = np.asarray(ends) - np.asarray(starts)
    assert (lengths == single_lengths).all()

    # identical rows per bucket (as multisets)
    dist_df = columnar.to_arrow(built).to_pandas()
    single_df = columnar.to_arrow(single).to_pandas()
    db = np.repeat(np.arange(16), lengths)
    sb = np.repeat(np.arange(16), single_lengths)
    dist_df["b"] = db
    single_df["b"] = sb
    cols = ["b", "k", "v", "s"]
    a = dist_df[cols].sort_values(cols).reset_index(drop=True)
    b = single_df[cols].sort_values(cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b)


def test_distributed_build_sorted_within_buckets(mesh):
    batch = make_batch(500, seed=4, with_strings=False)
    built, lengths = distributed_build(batch, ["k"], 8, mesh)
    k = np.asarray(built.column("k").data)
    start = 0
    for b in range(8):
        seg = k[start:start + lengths[b]]
        assert (np.diff(seg) >= 0).all(), f"bucket {b} not sorted"
        start += lengths[b]


def test_distributed_build_capacity_overflow_retry(mesh):
    """Skewed keys (all rows -> one bucket) overflow the default capacity;
    the exact-retry path must still deliver every row."""
    n = 800
    batch = columnar.from_arrow(pa.table({
        "k": np.full(n, 7, dtype=np.int64),
        "v": np.arange(n, dtype=np.float64),
    }))
    built, lengths = distributed_build(batch, ["k"], 16, mesh,
                                       capacity_factor=0.5)
    assert built.num_rows == n
    assert int(lengths.sum()) == n
    assert int(lengths.max()) == n  # all in one bucket


def test_distributed_join_matches_pandas(mesh):
    left = make_batch(600, seed=5, with_strings=False)
    right = make_batch(300, seed=6, with_strings=False)
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb, rl = distributed_build(right, ["k"], 16, mesh)
    li, ri = distributed_bucketed_join_indices(lb, rb, ll, rl, ["k"], ["k"],
                                               mesh)
    lk = np.asarray(lb.column("k").data)[np.asarray(li)]
    rk = np.asarray(rb.column("k").data)[np.asarray(ri)]
    assert (lk == rk).all()
    ref = pd.DataFrame({"k": np.asarray(lb.column("k").data)}).merge(
        pd.DataFrame({"k": np.asarray(rb.column("k").data)}), on="k")
    assert len(ref) == len(np.asarray(li))


def _indices_oracle(lb, rb, how):
    lk = pd.DataFrame({"k": np.asarray(lb.column("k").data),
                       "li": np.arange(lb.num_rows)})
    rk = pd.DataFrame({"k": np.asarray(rb.column("k").data),
                       "ri": np.arange(rb.num_rows)})
    merged = lk.merge(rk, on="k", how={"inner": "inner",
                                       "left_outer": "left",
                                       "full_outer": "outer"}[how])
    return merged


def test_distributed_full_outer_matches_pandas(mesh):
    left = make_batch(500, seed=8, with_strings=False)
    right = make_batch(260, seed=9, with_strings=False)
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb, rl = distributed_build(right, ["k"], 16, mesh)
    li, ri = distributed_bucketed_join_indices(lb, rb, ll, rl, ["k"], ["k"],
                                               mesh, how="full_outer")
    got = pd.DataFrame({"li": np.asarray(li), "ri": np.asarray(ri)})
    exp = _indices_oracle(lb, rb, "full_outer")
    exp = exp.fillna(-1).astype({"li": "int64", "ri": "int64"})
    key = ["li", "ri"]
    pd.testing.assert_frame_equal(
        got.sort_values(key).reset_index(drop=True),
        exp[key].sort_values(key).reset_index(drop=True),
        check_dtype=False)


def test_distributed_semi_anti_matches_pandas(mesh):
    from hyperspace_tpu.parallel.join import distributed_semi_anti_indices

    left = make_batch(500, seed=10, with_strings=False)
    right = make_batch(120, seed=11, with_strings=False)
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb, rl = distributed_build(right, ["k"], 16, mesh)
    lk = np.asarray(lb.column("k").data)
    rset = set(np.asarray(rb.column("k").data))
    for anti in (False, True):
        li = distributed_semi_anti_indices(lb, rb, ll, rl, ["k"], ["k"],
                                           mesh, anti=anti)
        got = sorted(np.asarray(li))
        member = np.asarray([k in rset for k in lk])
        exp = sorted(np.nonzero(~member if anti else member)[0])
        assert got == exp, f"anti={anti}"


def test_distributed_join_hot_bucket_skew(mesh):
    """A hot key concentrating most rows in ONE bucket must still join
    correctly through the sharded path (the [S, C] layout pads only the
    owner shard, not every bucket)."""
    n = 1200
    hot = np.full(n - 100, 7, dtype=np.int64)
    rest = np.arange(100, dtype=np.int64) + 100
    left = columnar.from_arrow(pa.table({
        "k": np.concatenate([hot, rest]),
        "v": np.arange(n, dtype=np.float64)}))
    right = columnar.from_arrow(pa.table({
        "k": np.asarray([7, 7, 120, 150], dtype=np.int64),
        "w": np.arange(4, dtype=np.float64)}))
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb, rl = distributed_build(right, ["k"], 16, mesh)
    li, ri = distributed_bucketed_join_indices(lb, rb, ll, rl, ["k"], ["k"],
                                               mesh, how="inner")
    lk = np.asarray(lb.column("k").data)[np.asarray(li)]
    rk = np.asarray(rb.column("k").data)[np.asarray(ri)]
    assert (lk == rk).all()
    # hot key expands (n-100)*2; the two singles match once each
    assert len(np.asarray(li)) == (n - 100) * 2 + 2


def test_distributed_join_memory_is_sharded(mesh):
    """The round-3 design replicated both sides' key lanes to every
    device (per-chip O(total rows)); the [S, C] layout must give every
    device ~1/S of the cells — assert the actual per-shard bytes."""
    from hyperspace_tpu.parallel.join import _sharded_inputs

    left = make_batch(4000, seed=12, with_strings=False)
    right = make_batch(2000, seed=13, with_strings=False)
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb, rl = distributed_build(right, ["k"], 16, mesh)
    lanes2d, pad, null, l_idx, r_idx, Cl, Cr, shard_rows = _sharded_inputs(
        lb, rb, ll, rl, ["k"], ["k"], mesh)
    assert len(shard_rows) == 8 and sum(shard_rows) >= lb.num_rows
    for arr in (*lanes2d, pad, null, l_idx, r_idx):
        shards = arr.addressable_shards
        assert len(shards) == 8
        per_dev = max(s.data.nbytes for s in shards)
        assert per_dev <= arr.nbytes / 8 + 1024, (
            f"device holds {per_dev}B of a {arr.nbytes}B array — "
            "not sharded")
    # and the layout itself is tight: padded cells within 2x of true rows
    S = 8
    assert S * (Cl + Cr) <= 2 * (lb.num_rows + rb.num_rows) + S


def test_distributed_join_empty_sides(mesh):
    """Empty sides must not reach the mesh layout (review regression:
    fancy-indexing a length-0 lane array raised IndexError)."""
    from hyperspace_tpu.parallel.join import distributed_semi_anti_indices

    left = make_batch(300, seed=14, with_strings=False)
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    empty = columnar.from_arrow(pa.table({
        "k": np.zeros(0, dtype=np.int64), "v": np.zeros(0)}))
    el = np.zeros(16, dtype=np.int64)
    li, ri = distributed_bucketed_join_indices(lb, empty, ll, el, ["k"],
                                               ["k"], mesh, how="inner")
    assert len(np.asarray(li)) == 0
    li, ri = distributed_bucketed_join_indices(lb, empty, ll, el, ["k"],
                                               ["k"], mesh,
                                               how="left_outer")
    assert (np.asarray(ri) == -1).all() and len(np.asarray(li)) == 300
    li, ri = distributed_bucketed_join_indices(empty, lb, el, ll, ["k"],
                                               ["k"], mesh,
                                               how="full_outer")
    assert (np.asarray(li) == -1).all() and len(np.asarray(ri)) == 300
    assert sorted(np.asarray(ri).tolist()) == list(range(300))
    anti = distributed_semi_anti_indices(lb, empty, ll, el, ["k"], ["k"],
                                         mesh, anti=True)
    assert len(np.asarray(anti)) == 300
    semi = distributed_semi_anti_indices(lb, empty, ll, el, ["k"], ["k"],
                                         mesh, anti=False)
    assert len(np.asarray(semi)) == 0


def test_hot_bucket_splits_across_shards(mesh):
    """One key holding 90% of the rows must NOT forfeit the mesh: the
    hot bucket's rows split across shards (replicating the other side's
    bucket rows), per-shard capacity stays <= 2x ideal, and the join
    result equals the single-chip counting join (round-4 review item 5)."""
    from hyperspace_tpu.ops.bucketed_join import bucketed_sort_merge_join
    from hyperspace_tpu.parallel.join import (
        _rows_to_layout, distributed_bucketed_join_indices,
        distributed_semi_anti_indices, shard_plan)

    n = 4000
    rng = np.random.default_rng(11)
    hot_k = np.where(rng.random(n) < 0.9, 7, rng.integers(0, 64, n))
    left = columnar.from_arrow(pa.table({
        "k": hot_k.astype(np.int64), "v": rng.random(n)}))
    m = 400
    rk = np.where(rng.random(m) < 0.5, 7, rng.integers(0, 64, m))
    right = columnar.from_arrow(pa.table({
        "k": rk.astype(np.int64), "w": rng.random(m)}))
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb, rl = distributed_build(right, ["k"], 16, mesh)

    # Capacity bound: the [S, C] layout stays near-balanced.
    for split in ("left", "larger"):
        l_rows, r_rows = shard_plan(ll, rl, 8, split)
        _, _, cl = _rows_to_layout(l_rows)
        _, _, cr = _rows_to_layout(r_rows)
        ideal = (int(ll.sum()) + int(rl.sum()) + 7) // 8
        assert cl + cr <= 2 * ideal, (split, cl, cr, ideal)

    for how in ("inner", "left_outer"):
        from hyperspace_tpu.ops.bucketed_join import assemble_join_output
        li, ri = distributed_bucketed_join_indices(
            lb, rb, ll, rl, ["k"], ["k"], mesh, how=how)
        got = assemble_join_output(lb, rb, li, ri, how=how)
        expected = bucketed_sort_merge_join(lb, rb, ll, rl, ["k"], ["k"],
                                            how=how)
        g = columnar.to_arrow(got).to_pandas()
        e = columnar.to_arrow(expected).to_pandas()
        cols = list(g.columns)
        pd.testing.assert_frame_equal(
            g.sort_values(cols).reset_index(drop=True),
            e.sort_values(cols).reset_index(drop=True), check_dtype=False)

    # Membership over the same skew: anti needs the FULL right set per
    # left row (left-only splitting) — counts must match single-chip.
    from hyperspace_tpu.ops.join import semi_anti_indices
    for anti in (False, True):
        idx = distributed_semi_anti_indices(lb, rb, ll, rl, ["k"], ["k"],
                                            mesh, anti=anti)
        ref = semi_anti_indices(lb, rb, ["k"], ["k"], anti=anti)
        assert sorted(np.asarray(idx).tolist()) == sorted(
            np.asarray(ref).tolist())


def test_shard_skew_guard():
    from hyperspace_tpu.parallel.join import (SKEW_BLOWUP_FACTOR,
                                              SKEW_MIN_CELLS, shard_skew)
    B, S = 16, 8
    even = np.full(B, SKEW_MIN_CELLS // B, dtype=np.int64)
    assert not shard_skew(even, even, S)
    # one bucket holds everything: cells = S * total >> rows
    hot = np.zeros(B, dtype=np.int64)
    hot[3] = SKEW_MIN_CELLS
    tiny = np.ones(B, dtype=np.int64)
    assert shard_skew(hot, tiny, S)
    assert SKEW_BLOWUP_FACTOR < S  # the guard bites before replication


def test_rebucket_mismatched_counts(mesh):
    """The ranker's fallback: re-bucket one side to the other's count."""
    batch = make_batch(400, seed=7, with_strings=False)
    rebucketed, lengths = rebucket(batch, ["k"], 32, mesh)
    assert rebucketed.num_rows == 400
    assert len(lengths) == 32
    assert int(lengths.sum()) == 400


def test_graft_entry():
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    out = fn(*args)
    assert out[0].shape[0] == 4096
    __graft_entry__.dryrun_multichip(8)


def test_distributed_group_aggregate_matches_single_chip(mesh):
    """SPMD partial aggregation + host combine must equal the single-chip
    aggregation for every combinable function, incl. stddev over
    large-offset values (exact variance decomposition) and null inputs."""
    import pandas as pd
    import pyarrow as pa

    from hyperspace_tpu.io.columnar import from_arrow, to_arrow
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.parallel.aggregate import distributed_group_aggregate
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    rng = np.random.default_rng(31)
    n = 20_000
    table = pa.table({
        "g": rng.integers(0, 97, n).astype(np.int64),
        "h": pa.array([["a", "b", "c"][i % 3] for i in range(n)]),
        "x": pa.array([None if i % 13 == 0 else 1.7e6 + float(v)
                       for i, v in enumerate(rng.standard_normal(n))],
                      type=pa.float64()),
        "y": rng.integers(-1000, 1000, n).astype(np.int64),
    })
    schema = Schema.from_arrow(table.schema)
    specs = [AggSpec("count", "*", "cnt"), AggSpec("count", "x", "cx"),
             AggSpec("sum", "y", "sy"), AggSpec("avg", "x", "ax"),
             AggSpec("min", "y", "mny"), AggSpec("max", "y", "mxy"),
             AggSpec("stddev", "x", "sx")]
    out_schema = Aggregate(["g", "h"], specs,
                           Scan(["/nx"], schema)).schema
    batch = from_arrow(table)
    dist = distributed_group_aggregate(batch, ["g", "h"], specs,
                                       out_schema, mesh)
    single = group_aggregate(batch, ["g", "h"], specs, out_schema)

    d = (to_arrow(dist).to_pandas().sort_values(["g", "h"])
         .reset_index(drop=True))
    s = (to_arrow(single).to_pandas().sort_values(["g", "h"])
         .reset_index(drop=True))
    pd.testing.assert_frame_equal(d, s, check_dtype=False,
                                  check_exact=False, rtol=1e-9)


def test_distributed_aggregate_int64_exact(mesh):
    """int64 sum/min/max past 2^53 must stay exact under distribution
    (float64 accumulation would silently round)."""
    import pyarrow as pa

    from hyperspace_tpu.io.columnar import from_arrow, to_arrow
    from hyperspace_tpu.ops.aggregate import group_aggregate
    from hyperspace_tpu.parallel.aggregate import distributed_group_aggregate
    from hyperspace_tpu.plan.nodes import Aggregate, AggSpec, Scan
    from hyperspace_tpu.plan.schema import Schema

    big = (1 << 53) + 1
    table = pa.table({"g": np.zeros(8, np.int64),
                      "y": np.array([big, big, big, big,
                                     big + 2, big + 2, big + 2, big + 2],
                                    dtype=np.int64)})
    schema = Schema.from_arrow(table.schema)
    specs = [AggSpec("sum", "y", "sy"), AggSpec("min", "y", "mny"),
             AggSpec("max", "y", "mxy")]
    out_schema = Aggregate(["g"], specs, Scan(["/nx"], schema)).schema
    batch = from_arrow(table)
    d = to_arrow(distributed_group_aggregate(batch, ["g"], specs,
                                             out_schema, mesh)).to_pandas()
    s = to_arrow(group_aggregate(batch, ["g"], specs,
                                 out_schema)).to_pandas()
    assert int(d.sy[0]) == int(s.sy[0]) == 8 * big + 8
    assert int(d.mny[0]) == big and int(d.mxy[0]) == big + 2


def test_distributed_left_outer_join_with_nulls(mesh):
    """Mesh left_outer: unmatched and null-key left rows emit right -1;
    matches equal pandas. Exercises the shard-local per-bucket encode's
    null-group forcing."""
    rng = np.random.default_rng(9)
    lk = rng.integers(0, 30, 400).astype(np.float64)
    lk[::17] = np.nan  # null keys via mask below
    lmask = ~np.isnan(lk)
    left = columnar.from_arrow(pa.table({
        "k": pa.array(np.where(lmask, lk, 0).astype(np.int64),
                      mask=~lmask),
        "x": rng.random(400)}))
    right = columnar.from_arrow(pa.table({
        "k": rng.integers(10, 50, 150).astype(np.int64),
        "y": rng.random(150)}))
    lb, ll = distributed_build(left, ["k"], 16, mesh)
    rb, rl = distributed_build(right, ["k"], 16, mesh)
    li, ri = distributed_bucketed_join_indices(lb, rb, ll, rl, ["k"], ["k"],
                                               mesh, how="left_outer")
    li, ri = np.asarray(li), np.asarray(ri)
    lkey = np.asarray(lb.column("k").data)
    lval = (np.asarray(lb.column("k").validity)
            if lb.column("k").validity is not None
            else np.ones(len(lkey), bool))
    rkey = np.asarray(rb.column("k").data)
    # pandas oracle over the built layouts
    lpd = pd.DataFrame({"k": np.where(lval, lkey, -999),
                        "li": np.arange(len(lkey)),
                        "valid": lval})
    rpd = pd.DataFrame({"k": rkey, "ri": np.arange(len(rkey))})
    matched = lpd[lpd.valid].merge(rpd, on="k")
    exp_pairs = set(zip(matched.li.tolist(), matched.ri.tolist()))
    got_matched = {(int(a), int(b)) for a, b in zip(li, ri) if b >= 0}
    assert got_matched == exp_pairs
    # every left row appears at least once; unmatched exactly once with -1
    got_left_counts = pd.Series(li).value_counts()
    assert set(got_left_counts.index) == set(range(len(lkey)))
    unmatched_left = set(range(len(lkey))) - set(matched.li)
    for row in unmatched_left:
        assert got_left_counts[row] == 1


# -- two-axis (dcn x shard) mesh: multi-host topology ---------------------


@pytest.fixture(scope="module")
def mesh24():
    from hyperspace_tpu.parallel.mesh import make_mesh
    return make_mesh(8, dcn_size=2)


def test_two_axis_build_matches_single_chip(mesh24):
    from hyperspace_tpu.ops.build import build_sorted

    batch = make_batch(900, seed=21, with_strings=True)
    built, lengths = distributed_build(batch, ["k"], 16, mesh24)
    single, starts, ends = build_sorted(batch, ["k"], 16)
    sl = np.asarray(ends) - np.asarray(starts)
    assert (lengths == sl).all()
    cols = ["k", "v", "s"]
    a = columnar.to_arrow(built).to_pandas()[cols].sort_values(cols) \
        .reset_index(drop=True)
    b = columnar.to_arrow(single).to_pandas()[cols].sort_values(cols) \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b)


def test_two_axis_join_matches_pandas(mesh24):
    left = make_batch(700, seed=22, with_strings=False)
    right = make_batch(350, seed=23, with_strings=False)
    lb, ll = distributed_build(left, ["k"], 16, mesh24)
    rb, rl = distributed_build(right, ["k"], 16, mesh24)
    li, ri = distributed_bucketed_join_indices(lb, rb, ll, rl, ["k"], ["k"],
                                               mesh24)
    lk = np.asarray(lb.column("k").data)
    rk = np.asarray(rb.column("k").data)
    assert (lk[np.asarray(li)] == rk[np.asarray(ri)]).all()
    exp = pd.DataFrame({"k": lk}).merge(pd.DataFrame({"k": rk}), on="k")
    assert len(exp) == len(np.asarray(li))


def test_two_axis_collectives_confined_to_axes(mesh24):
    """SURVEY §2.12 "DCN only across slices": the build's heavy re-bucket
    all_to_all must be CONFINED to the inner (ICI) axis — replica groups
    {0..3},{4..7} — with only the slim cross-slice stage over DCN pairs
    {0,4},{1,5},... . Asserted on the COMPILED HLO's replica groups."""
    import re

    import jax.numpy as jnp

    from hyperspace_tpu.io.columnar import batch_to_tree
    from hyperspace_tpu.parallel.build import make_distributed_build_step

    batch = make_batch(1024, seed=24, with_strings=False)
    tree, _ = batch_to_tree(batch)
    in_tree = {name: dict(e, data=jnp.asarray(e["data"]))
               for name, e in tree.items()}
    in_tree["__valid__"] = jnp.ones(1024, dtype=bool)
    step = make_distributed_build_step(mesh24, ("k",), 16, 2.0)
    hlo = step.lower(in_tree).compile().as_text()
    groups = set(re.findall(r"replica_groups=(\{\{[0-9,{}]*\}\})", hlo))
    assert "{{0,1,2,3},{4,5,6,7}}" in groups, groups  # ICI stage
    assert "{{0,4},{1,5},{2,6},{3,7}}" in groups, groups  # DCN stage
    flat = "{{0,1,2,3,4,5,6,7}}"
    assert flat not in groups, "a collective spans the full mesh"
