"""Serving-plane resilience: admission control, deadlines & cooperative
cancellation, backpressure, the degradation circuit breaker, and the
chaos run over the virtual 8-device mesh.

The acceptance bar this suite pins (ISSUE 7): >=8 client threads x
>=200 mixed queries with fault injection active — zero deadlocks, zero
HBM-budget breaches, every successful query bit-identical to its
serial run, and every rejected/timed-out query surfaced as a TYPED
error with a matching `serve.*` counter.
"""

import os
import shutil
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import (Hyperspace, HyperspaceConf, HyperspaceSession,
                            IndexConfig, telemetry)
from hyperspace_tpu.engine import scheduler as sched_mod
from hyperspace_tpu.engine.scheduler import (Deadline, QueryScheduler,
                                             _QueryEntry)
from hyperspace_tpu.exceptions import (HyperspaceException,
                                       QueryCancelledError,
                                       QueryDeadlineExceededError,
                                       QueryRejectedError)
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.utils.faults import FaultRule

from chaos import canonical, run_chaos

MIB = 1024 * 1024


def _counter(name):
    return telemetry.get_registry().counters_dict().get(name, 0)


@pytest.fixture
def fresh_scheduler():
    """A scheduler with clean budgets/breakers for this test; a fresh
    one is installed again on teardown so no state leaks either way."""
    sch = sched_mod.set_scheduler(QueryScheduler())
    yield sch
    sched_mod.set_scheduler(QueryScheduler())


@pytest.fixture
def serving_env(tmp_path):
    """facts/dims parquet + a session factory taking conf overrides."""
    rng = np.random.default_rng(11)
    n = 50_000
    n_dims = 500
    facts_dir = tmp_path / "facts"
    dims_dir = tmp_path / "dims"
    facts_dir.mkdir()
    dims_dir.mkdir()
    pq.write_table(pa.table({
        "k": rng.integers(0, n_dims, n).astype(np.int64),
        "g": rng.integers(0, 16, n).astype(np.int64),
        "v": rng.random(n).astype(np.float64),
    }), str(facts_dir / "part-0.parquet"))
    pq.write_table(pa.table({
        "k": np.arange(n_dims, dtype=np.int64),
        "w": rng.random(n_dims).astype(np.float64),
    }), str(dims_dir / "part-0.parquet"))

    def session(**extra):
        conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh")}
        conf.update({k: str(v) for k, v in extra.items()})
        return HyperspaceSession(HyperspaceConf(conf))

    return session, str(facts_dir), str(dims_dir)


def _hold(sch, nbytes, qid="blocker"):
    """Manually occupy `nbytes` of the serving budget (a stand-in for a
    long-running admitted query). Returns the entry for `_release`."""
    ent = _QueryEntry(qid, Deadline(qid), nbytes, None)
    with sch._cv:
        sch._active[qid] = ent
        sch._grant(ent, telemetry.get_registry())
    return ent


# ---------------------------------------------------------------------------
# Deadline primitive
# ---------------------------------------------------------------------------


def test_deadline_expiry_and_cancel_are_typed():
    d = Deadline("q-x", timeout_s=0.01)
    d.check("scan")  # not yet expired
    time.sleep(0.015)
    with pytest.raises(QueryDeadlineExceededError) as ei:
        d.check("transfer")
    assert ei.value.phase == "transfer"
    assert ei.value.query_id == "q-x"

    d2 = Deadline("q-y")  # no time limit: cancel-only
    assert d2.remaining() is None
    d2.check("stage")
    d2.cancel()
    with pytest.raises(QueryCancelledError) as ei:
        d2.check("write")
    assert ei.value.phase == "write"
    # the deadline error IS a cancellation (one except catches both)
    assert issubclass(QueryDeadlineExceededError, QueryCancelledError)


def test_deadline_propagates_to_pool_threads():
    d = Deadline("q-z")
    d.cancel()
    seen = []

    def probe():
        try:
            telemetry.check_deadline("operator")
            seen.append("no-raise")
        except QueryCancelledError as exc:
            seen.append(exc.phase)

    with telemetry.deadline_scope(d):
        wrapped = telemetry.propagating(probe)
    t = threading.Thread(target=wrapped)
    t.start()
    t.join(5)
    assert seen == ["operator"]
    # outside the scope the checkpoint is a no-op
    telemetry.check_deadline("operator")


# ---------------------------------------------------------------------------
# Admission control (unit level: deterministic FIFO / reject semantics)
# ---------------------------------------------------------------------------


def test_admission_fifo_queue_and_reject(fresh_scheduler):
    sch = fresh_scheduler
    conf = HyperspaceConf({
        "spark.hyperspace.serve.hbm.budget.bytes": "100",
        "spark.hyperspace.serve.queue.depth": "1"})
    e1 = _QueryEntry("q1", Deadline("q1"), 60, None)
    assert sch._admit(e1, conf) == 0.0
    assert sch.admitted_bytes() == 60

    admitted = threading.Event()

    def queued_worker():
        e2 = _QueryEntry("q2", Deadline("q2"), 60, None)
        sch._admit(e2, conf)
        admitted.set()
        sch._release(e2)

    t = threading.Thread(target=queued_worker)
    t.start()
    for _ in range(200):  # wait until q2 is genuinely queued
        with sch._cv:
            if sch._waiters:
                break
        time.sleep(0.005)
    assert not admitted.is_set()

    # Queue full (depth 1): immediate typed backpressure.
    e3 = _QueryEntry("q3", Deadline("q3"), 60, None)
    with pytest.raises(QueryRejectedError) as ei:
        sch._admit(e3, conf)
    assert ei.value.phase == "queue"

    # Release the holder: the queued query admits (FIFO head).
    sch._release(e1)
    assert admitted.wait(5.0)
    t.join(5)
    assert sch.admitted_bytes() == 0

    # A query whose deadline expires while QUEUED raises typed too.
    e_hold = _hold(sch, 100)
    try:
        e4 = _QueryEntry("q4", Deadline("q4", timeout_s=0.05), 60, None)
        with pytest.raises(QueryDeadlineExceededError) as ei:
            sch._admit(e4, conf)
        assert ei.value.phase == "queue"
    finally:
        sch._release(e_hold)


def test_oversized_query_still_admits_when_idle(fresh_scheduler):
    """Progress guarantee: the budget bounds concurrency, it must never
    wedge serving — a query bigger than the whole budget runs alone."""
    sch = fresh_scheduler
    conf = HyperspaceConf({
        "spark.hyperspace.serve.hbm.budget.bytes": "100"})
    big = _QueryEntry("big", Deadline("big"), 10_000, None)
    assert sch._admit(big, conf) == 0.0
    sch._release(big)


# ---------------------------------------------------------------------------
# End-to-end: collect under budget pressure
# ---------------------------------------------------------------------------


def test_collect_backpressure_and_queue_deadline(serving_env,
                                                 fresh_scheduler):
    session, facts_dir, _dims = serving_env
    sess = session(**{
        "spark.hyperspace.serve.hbm.budget.bytes": 2 * MIB,
        "spark.hyperspace.serve.queue.depth": 0})
    df = sess.read_parquet(facts_dir).select("k")
    df.collect()  # warm; admits alone

    sch = fresh_scheduler
    holder = _hold(sch, 2 * MIB)
    try:
        rejected_before = _counter("serve.rejected")
        with pytest.raises(QueryRejectedError) as ei:
            df.collect()
        assert ei.value.phase == "queue"
        assert _counter("serve.rejected") == rejected_before + 1

        # With queue room, the query WAITS — and its deadline fires in
        # the queue, typed, with the queue phase attributed.
        sess.conf.set("spark.hyperspace.serve.queue.depth", "4")
        exceeded_before = _counter("serve.deadline_exceeded")
        with pytest.raises(QueryDeadlineExceededError) as ei:
            df.collect(timeout=0.05)
        assert ei.value.phase == "queue"
        assert _counter("serve.deadline_exceeded") == exceeded_before + 1
        assert _counter("serve.interrupted.queue") >= 1
    finally:
        sch._release(holder)
    # Budget freed: serving resumes.
    assert df.collect().num_rows > 0


def test_cancel_queued_query_via_session(serving_env, fresh_scheduler):
    session, facts_dir, _dims = serving_env
    sess = session(**{
        "spark.hyperspace.serve.hbm.budget.bytes": 2 * MIB,
        "spark.hyperspace.serve.queue.depth": 4})
    df = sess.read_parquet(facts_dir).select("k")
    df.collect()  # warm

    sch = fresh_scheduler
    holder = _hold(sch, 2 * MIB)
    outcome = {}

    def worker():
        try:
            df.collect()
            outcome["result"] = "finished"
        except QueryCancelledError as exc:
            outcome["result"] = exc

    t = threading.Thread(target=worker)
    try:
        t.start()
        target = None
        for _ in range(400):
            live = [q for q in sess.active_queries() if q != "blocker"]
            if live:
                target = live[0]
                break
            time.sleep(0.005)
        assert target is not None, "query never registered"
        assert sess.cancel(target) is True
        t.join(10)
        assert not t.is_alive()
        exc = outcome["result"]
        assert isinstance(exc, QueryCancelledError)
        assert exc.phase == "queue"
        assert sess.cancel(target) is False  # gone from the registry
    finally:
        sch._release(holder)


# ---------------------------------------------------------------------------
# Deadline mid-execution + telemetry isolation (the satellite test)
# ---------------------------------------------------------------------------


def _join_query(sess, facts_dir, dims_dir):
    facts = sess.read_parquet(facts_dir)
    dims = sess.read_parquet(dims_dir)
    return facts.join(dims, on="k").filter(col("w") > lit(0.25)) \
        .group_by("g").agg(("sum", "v", "total"), cnt=("count", "*"))


def test_deadline_mid_query_is_typed_and_flight_recorded(
        serving_env, fresh_scheduler):
    session, facts_dir, dims_dir = serving_env
    sess = session()
    df = _join_query(sess, facts_dir, dims_dir)
    df.collect()  # warm caches + jit so the timed run is steady-state

    before = _counter("serve.deadline_exceeded")
    with pytest.raises(QueryDeadlineExceededError) as ei:
        df.collect(timeout=0.002)
    exc = ei.value
    assert exc.phase in ("plan", "scan", "operator", "stage",
                         "transfer", "write", "queue", "batch")
    assert _counter("serve.deadline_exceeded") == before + 1
    assert _counter(f"serve.interrupted.{exc.phase}") >= 1

    # The cancelled query's recorder joined the flight ring WITH the
    # interrupted phase — that is what lets bench_diff attribute a
    # timeout cluster to a bucket instead of residual.
    ring = telemetry.get_recorder().queries(5)
    dumped = [m for m in ring
              if getattr(m, "query_id", None) == exc.query_id]
    assert dumped, "cancelled query missing from the flight ring"
    ev = dumped[-1].events_of("serve", "deadline_exceeded")
    assert ev and ev[-1]["phase"] == exc.phase
    assert dumped[-1].counters.get(
        f"serve.interrupted.{exc.phase}") == 1


def test_concurrent_deadline_and_survivor_isolation(
        serving_env, fresh_scheduler, leak_sentinel):
    """Satellite: two threads on ONE session — one hits its deadline
    mid-join, the other succeeds; the survivor's telemetry is
    unpolluted and the cancelled query's device buffers are freed."""
    session, facts_dir, dims_dir = serving_env
    sess = session()
    victim_df = _join_query(sess, facts_dir, dims_dir)
    survivor_df = sess.read_parquet(facts_dir) \
        .filter(col("v") > lit(0.5)).select("k", "v")
    victim_df.collect()    # warm both paths first
    expected = canonical(survivor_df.collect())

    results = {}

    def victim():
        try:
            victim_df.collect(timeout=0.002)
            results["victim"] = "finished"  # fast machine: not a fail
        except QueryDeadlineExceededError as exc:
            results["victim"] = exc

    def survivor():
        results["survivor"] = survivor_df.collect(with_metrics=True)

    with leak_sentinel(tolerance=8):
        for _ in range(3):  # steady state must not accrete arrays
            t1 = threading.Thread(target=victim)
            t2 = threading.Thread(target=survivor)
            t1.start()
            t2.start()
            t1.join(30)
            t2.join(30)
            assert not t1.is_alive() and not t2.is_alive()

    exc = results["victim"]
    assert isinstance(exc, QueryDeadlineExceededError), \
        f"victim outcome: {exc!r}"
    table, m = results["survivor"]
    assert canonical(table).equals(expected)
    # Survivor's recorder: its own identity, no interruption markers,
    # exactly one admission event — and not the victim's.
    assert m.query_id != exc.query_id
    assert not any(k.startswith("serve.interrupted")
                   for k in m.counters)
    admitted = m.events_of("serve", "admitted")
    assert len(admitted) == 1
    assert admitted[0]["query_id"] == m.query_id
    assert not m.events_of("serve", "deadline_exceeded")


# ---------------------------------------------------------------------------
# Degradation circuit breaker
# ---------------------------------------------------------------------------


def _indexed_env(tmp_path, **conf_extra):
    rng = np.random.default_rng(5)
    src = tmp_path / "src"
    src.mkdir()
    pq.write_table(pa.table({
        "k": rng.integers(0, 40, 4000).astype(np.int64),
        "x": rng.random(4000).astype(np.float64),
    }), str(src / "part-0.parquet"))
    conf = {"hyperspace.warehouse.dir": str(tmp_path / "wh"),
            "hyperspace.index.num.buckets": "4"}
    conf.update({k: str(v) for k, v in conf_extra.items()})
    sess = HyperspaceSession(HyperspaceConf(conf))
    hs = Hyperspace(sess)
    df = sess.read_parquet(str(src))
    hs.create_index(df, IndexConfig("idx", ["k"], ["x"]))
    sess.enable_hyperspace()
    query = lambda: df.filter(col("k") == lit(7)).select("x")
    idx_data = str(tmp_path / "wh" / "indexes" / "idx" / "v__=0")
    return sess, query, idx_data


def test_breaker_opens_short_circuits_probes_and_closes(
        tmp_path, fresh_scheduler):
    sess, query, idx_data = _indexed_env(
        tmp_path,
        **{"spark.hyperspace.serve.breaker.failures": 2,
           "spark.hyperspace.serve.breaker.window.seconds": 60,
           "spark.hyperspace.serve.breaker.cooldown.seconds": 0.05})
    want = canonical(query().collect())
    backup = str(tmp_path / "backup_v0")
    shutil.copytree(idx_data, backup)
    shutil.rmtree(idx_data)

    c0 = {k: _counter(k) for k in (
        "resilience.fallbacks", "resilience.breaker.opened",
        "resilience.breaker.half_open", "resilience.breaker.closed",
        "resilience.breaker.short_circuits")}

    # Failures 1 & 2: the expensive fallback path, breaker counting.
    for i in range(2):
        assert canonical(query().collect()).equals(want)
    assert _counter("resilience.fallbacks") - \
        c0["resilience.fallbacks"] == 2
    assert _counter("resilience.breaker.opened") - \
        c0["resilience.breaker.opened"] == 1

    # Open: the source answer WITHOUT re-paying the failed index scan.
    table, m = query().collect(with_metrics=True)
    assert canonical(table).equals(want)
    assert m.counters.get("resilience.breaker.short_circuits") == 1
    degraded = m.events_of("resilience", "degraded")
    assert degraded and degraded[-1]["reason"] == "breaker open"
    assert _counter("resilience.breaker.short_circuits") - \
        c0["resilience.breaker.short_circuits"] == 1

    # Cooldown -> half-open probe; index still broken -> re-opens.
    time.sleep(0.06)
    assert canonical(query().collect()).equals(want)
    assert _counter("resilience.breaker.half_open") - \
        c0["resilience.breaker.half_open"] == 1
    assert _counter("resilience.breaker.opened") - \
        c0["resilience.breaker.opened"] == 2

    # Repair the index; next probe succeeds -> breaker closes and the
    # index serves again.
    shutil.copytree(backup, idx_data)
    time.sleep(0.06)
    table, m = query().collect(with_metrics=True)
    assert canonical(table).equals(want)
    assert _counter("resilience.breaker.closed") - \
        c0["resilience.breaker.closed"] == 1
    assert m.counters.get("resilience.fallbacks") is None
    assert m.index_usage(), "closed breaker must serve from the index"


# ---------------------------------------------------------------------------
# Transfer engine: acquire timeout + reservation release (satellite)
# ---------------------------------------------------------------------------


class _NeverReady:
    """A 'device array' whose transfer never completes."""

    nbytes = 128

    def is_ready(self):
        return False


def test_transfer_acquire_timeout_is_typed_and_transient():
    from hyperspace_tpu.io import transfer
    from hyperspace_tpu.io.transfer import (TransferAcquireTimeoutError,
                                            _WindowEntry)
    from hyperspace_tpu.utils import retry

    eng = transfer.TransferEngine(chunk_bytes=64, inflight_bytes=128,
                                  put_fn=lambda a, d: np.asarray(a),
                                  acquire_timeout_s=0.05)
    # A transfer that died holding its bytes: the window is pinned full.
    dead = _WindowEntry(_NeverReady(), 128, None)
    with eng._lock:
        eng._window.append(dead)
        eng._window_bytes = 128

    before = _counter("io.transfer.acquire_timeouts")
    t0 = time.perf_counter()
    with pytest.raises(TransferAcquireTimeoutError) as ei:
        eng.put(np.zeros(64, dtype=np.uint8))
    assert time.perf_counter() - t0 < 5.0  # bounded, not forever
    assert _counter("io.transfer.acquire_timeouts") == before + 1
    # Typed TRANSIENT: the retry seam would back off and re-try it.
    assert retry.is_transient(ei.value)
    # The dead entry's accounting was preserved (nothing leaked out).
    assert eng._window_bytes == 128 and len(eng._window) == 1


def test_failed_put_releases_window_reservation():
    from hyperspace_tpu.io import transfer

    def dying_put(arr, device):
        raise RuntimeError("link died mid-put")

    eng = transfer.TransferEngine(chunk_bytes=1024,
                                  inflight_bytes=4096,
                                  put_fn=dying_put,
                                  acquire_timeout_s=0.2)
    with pytest.raises(RuntimeError):
        eng.put(np.zeros(256, dtype=np.uint8))
    # The reservation died with the put — later callers see a clean
    # window instead of permanently lost budget.
    assert eng._window_bytes == 0
    assert len(eng._window) == 0


def test_transfer_chunk_loop_honors_deadline():
    from hyperspace_tpu.io import transfer

    eng = transfer.TransferEngine(chunk_bytes=1024,
                                  inflight_bytes=1 << 20,
                                  put_fn=lambda a, d: np.asarray(a))
    d = Deadline("q-t")
    d.cancel()
    with telemetry.deadline_scope(d):
        with pytest.raises(QueryCancelledError) as ei:
            eng.put(np.zeros(1 << 16, dtype=np.uint8))  # 64 chunks
    assert ei.value.phase == "transfer"
    # All staged conversions were drained; no window bytes leaked.
    assert eng._window_bytes == 0


# ---------------------------------------------------------------------------
# Footprint estimation
# ---------------------------------------------------------------------------


def test_projected_footprint_scales_with_scan_bytes(tmp_path):
    from hyperspace_tpu.plan import footprint

    big_dir = tmp_path / "big"
    big_dir.mkdir()
    n = 400_000
    pq.write_table(pa.table({
        "a": np.arange(n, dtype=np.int64),
        "b": np.random.default_rng(0).random(n),
    }), str(big_dir / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf(
        {"hyperspace.warehouse.dir": str(tmp_path / "wh")}))
    df = sess.read_parquet(str(big_dir))
    size = os.path.getsize(str(big_dir / "part-0.parquet"))
    est = footprint.projected_bytes(df.plan)
    assert est >= size  # conservative: decoded >= on-disk
    assert est >= footprint.MIN_FOOTPRINT_BYTES
    # A join charges BOTH sides.
    est_join = footprint.projected_bytes(df.join(df, on="a").plan)
    assert est_join >= 2 * size


def test_projected_footprint_degrades_never_raises():
    from hyperspace_tpu.plan import footprint
    from hyperspace_tpu.plan.nodes import Scan
    from hyperspace_tpu.plan.schema import Schema, Field

    schema = Schema([Field("a", "int64")])
    ghost = Scan(["/nonexistent/path/xyz"], schema)
    est = footprint.projected_bytes(ghost)
    assert est >= footprint.MIN_FOOTPRINT_BYTES


# ---------------------------------------------------------------------------
# Session lifecycle
# ---------------------------------------------------------------------------


def test_session_close_is_idempotent_and_refuses_new_queries(
        serving_env, fresh_scheduler):
    session, facts_dir, _dims = serving_env
    sess = session()
    df = sess.read_parquet(facts_dir).select("k")
    assert df.collect().num_rows > 0
    sess.close()
    sess.close()  # idempotent
    with pytest.raises(HyperspaceException):
        df.collect()


# ---------------------------------------------------------------------------
# THE chaos run (acceptance): 8 clients x 240 mixed queries, faults on
# ---------------------------------------------------------------------------


def test_chaos_concurrent_serving_with_faults(serving_env,
                                              fresh_scheduler,
                                              fault_injector):
    session, facts_dir, dims_dir = serving_env
    budget = 64 * MIB
    sess = session(**{
        "spark.hyperspace.serve.hbm.budget.bytes": budget,
        "spark.hyperspace.serve.queue.depth": 16,
        "spark.hyperspace.io.retry.base.ms": 1,
        "spark.hyperspace.io.retry.max.ms": 5})
    facts = sess.read_parquet(facts_dir)
    dims = sess.read_parquet(dims_dir)
    workload = [
        ("filter", facts.filter(col("v") > lit(0.9)).select("k", "v")),
        ("agg", facts.group_by("g").agg(("sum", "v", "total"),
                                        cnt=("count", "*"))),
        ("join", facts.join(dims, on="k").filter(col("w") > lit(0.5))
         .group_by("g").agg(("avg", "v", "avg_v"))),
        ("topn", facts.sort("-v").limit(20).select("k", "v")),
        ("distinct", facts.select("g").distinct()),
    ]
    # Serial oracles BEFORE faults arm (clean expected results).
    expected = {name: canonical(df.collect()) for name, df in workload}

    counters0 = {k: _counter(k) for k in (
        "serve.rejected", "serve.deadline_exceeded", "serve.cancelled")}

    # Transients at every layer the serving plane must survive:
    # storage reads (retried under the io policy), fusion stage entry,
    # and the scheduler's own admission boundary.
    fault_injector(
        FaultRule("parquet.read:*", kind="transient", nth=1, times=-1,
                  probability=0.05),
        FaultRule("fusion.stage", kind="transient", nth=1, times=-1,
                  probability=0.02),
        FaultRule("scheduler.admit", kind="transient", nth=1, times=-1,
                  probability=0.01),
        seed=1234)

    clients, total = 8, 240
    report = run_chaos(
        workload, expected, clients=clients, total_queries=total,
        # Every 9th query gets a deadline it cannot meet: the typed
        # timeout path stays exercised under load, deterministically.
        timeout_for=lambda i: 0.0015 if i % 9 == 0 else None,
        join_timeout_s=300.0)

    # 1. No deadlock: every client thread came home.
    assert not report.stuck_threads, report.summary()
    assert report.total == total

    # 2. No silent failure modes: every non-ok outcome is typed (or an
    # injected fault that legitimately escaped the resilience layers).
    assert report.outcomes["error"] == 0, report.errors[:5]

    # 3. Correctness: every query that reported success is
    # bit-identical to its serial run.
    assert not report.mismatches, report.mismatches[:5]
    assert report.outcomes["ok"] >= total // 2, report.summary()

    # 4. The deadline path actually fired under load, typed.
    assert report.outcomes["deadline"] >= 1, report.summary()
    assert all(p in ("queue", "plan", "scan", "operator", "stage",
                     "transfer", "write", "batch")
               for p in report.typed_phases)

    # 5. Budget: the scheduler never admitted past it, and no
    # successful query's HBM watermark breached it.
    sch = sched_mod.get_scheduler()
    assert sch.peak_admitted_bytes <= budget
    assert sch.admitted_bytes() == 0  # fully drained
    peak_hbm = max((m.peak_hbm_bytes for m in report.success_metrics),
                   default=0)
    assert peak_hbm <= budget

    # 6. Every typed outcome has its matching serve.* counter delta —
    # exactly, not approximately.
    assert _counter("serve.rejected") - counters0["serve.rejected"] \
        == report.outcomes["rejected"]
    assert (_counter("serve.deadline_exceeded")
            - counters0["serve.deadline_exceeded"]) \
        == report.outcomes["deadline"]
    assert _counter("serve.cancelled") - counters0["serve.cancelled"] \
        == report.outcomes["cancelled"]

    # 7. No cross-query telemetry bleed: every success carries its own
    # unique identity, exactly one admission event (its own), and no
    # interruption markers from its cancelled neighbors.
    ids = [m.query_id for m in report.success_metrics]
    assert len(ids) == len(set(ids))
    for m in report.success_metrics:
        admitted = m.events_of("serve", "admitted")
        assert len(admitted) == 1
        assert admitted[0]["query_id"] == m.query_id
        assert not any(k.startswith("serve.interrupted")
                       for k in m.counters)
        assert m.wall_s is not None and m.operators
