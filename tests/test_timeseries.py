"""Timeseries sampler: window math vs a brute-force oracle (under
concurrent observers), ring bounds, counter rates, window gauges, and
the sampler thread's start/drain lifecycle."""

import math
import threading
import time

import numpy as np
import pytest

from hyperspace_tpu import telemetry
from hyperspace_tpu.telemetry import timeseries
from hyperspace_tpu.telemetry.timeseries import (TimeSeriesSampler,
                                                 delta_buckets,
                                                 quantile_from_buckets)


@pytest.fixture
def sampler():
    s = TimeSeriesSampler(interval_s=0.02, capacity=64, window_s=60.0)
    yield s
    s.drain()


def _observe(name, values):
    h = telemetry.get_registry().histogram(name)
    for v in values:
        h.observe(v)


def _oracle_quantile(values, q):
    """The brute-force definition the bucket walk must agree with: the
    ceil(q*n)-th order statistic (1-based)."""
    s = sorted(values)
    return s[max(1, math.ceil(q * len(s))) - 1]


def test_quantile_from_buckets_bound_vs_oracle():
    """For every q, the bucket quantile is the log2 UPPER bound of the
    bucket holding the q-th observation: oracle <= reported <
    2 * oracle (exact when the oracle value is a power of two)."""
    rng = np.random.default_rng(11)
    values = list(rng.lognormal(mean=-3.0, sigma=2.0, size=500)) \
        + [0.25, 1.0, 4.0]  # exact powers of two hit the bound
    buckets = {}
    for v in values:
        exp = math.ceil(math.log2(v)) if v > 0 else None
        buckets[exp] = buckets.get(exp, 0) + 1
    for q in (0.01, 0.25, 0.50, 0.90, 0.99, 1.0):
        oracle = _oracle_quantile(values, q)
        reported = quantile_from_buckets(buckets, q)
        assert reported >= oracle
        assert reported < 2 * oracle


def test_quantile_nonpositive_bucket_and_empty():
    assert quantile_from_buckets({}, 0.99) is None
    assert quantile_from_buckets({None: 5}, 0.5) == 0.0
    # Non-positive observations sort below every finite bucket.
    assert quantile_from_buckets({None: 99, 0: 1}, 0.5) == 0.0
    assert quantile_from_buckets({None: 1, 0: 99}, 0.99) == 1.0


def test_window_quantile_vs_oracle_under_concurrent_observes(sampler):
    """The E2E oracle test: N threads observe into one registry
    histogram while the sampler ticks; the window quantile computed
    from merged bucket deltas must bracket the brute-force quantile of
    exactly the observed values."""
    name = "query.wall_s"
    reg = telemetry.get_registry()
    base = reg.histogram(name).bucket_state()  # pre-test pollution
    sampler.tick()
    rng = np.random.default_rng(7)
    per_thread = [list(rng.lognormal(-4.0, 1.5, size=200))
                  for _ in range(4)]
    stop = threading.Event()

    def ticker():
        while not stop.is_set():
            sampler.tick()
            time.sleep(0.002)

    tick_thread = threading.Thread(target=ticker)
    tick_thread.start()
    threads = [threading.Thread(target=_observe, args=(name, vals))
               for vals in per_thread]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    tick_thread.join()
    sampler.tick()

    observed = [v for vals in per_thread for v in vals]
    # Window covering the whole run = cumulative now minus the
    # pre-test baseline: check the merged deltas count every observe.
    merged = delta_buckets(reg.histogram(name).bucket_state(), base)
    assert sum(merged.values()) == len(observed)
    for q in (0.50, 0.90, 0.99):
        oracle = _oracle_quantile(observed, q)
        reported = quantile_from_buckets(merged, q)
        assert oracle <= reported < 2 * oracle
    # The sampler's own trailing window (everything is recent) must
    # agree with the merged-delta answer.
    win = sampler.window_quantile(name, 0.99, window_s=3600.0)
    assert win is not None and win > 0


def test_ring_bounds_and_samples_order():
    s = TimeSeriesSampler(interval_s=0.01, capacity=8)
    for i in range(50):
        s.tick(t=1000.0 + i)
    assert len(s) == 8
    samples = s.samples()
    assert [x["t"] for x in samples] == sorted(x["t"] for x in samples)
    assert samples[0]["t"] == pytest.approx(1042.0)
    # since_t filters strictly-after.
    assert len(s.samples(since_t=1045.0)) == 4
    s.drain()


def test_counter_rates_and_window_rate():
    reg = telemetry.get_registry()
    c = reg.counter("serve.admitted")
    s = TimeSeriesSampler(interval_s=0.01, capacity=16)
    s.tick(t=100.0)
    c.inc(10)
    sample = s.tick(t=102.0)
    assert sample["rates"]["serve.admitted"] == pytest.approx(5.0)
    c.inc(30)
    s.tick(t=104.0)
    # Window rate over the trailing 4s: 40 increments / 4s.
    assert s.window_rate("serve.admitted", window_s=4.0) \
        == pytest.approx(10.0)
    s.drain()


def test_window_gauges_published(sampler):
    reg = telemetry.get_registry()
    reg.histogram("query.wall_s").observe(0.01)
    reg.counter("queries.total").inc()
    sampler.tick()
    reg.histogram("query.wall_s").observe(0.02)
    reg.counter("queries.total").inc()
    sampler.tick()
    gauges = reg.to_dict()["gauges"]
    assert gauges.get("window.query.wall_s.p99", 0) > 0
    assert gauges.get("window.query.wall_s.count", 0) >= 1
    assert "window.queries.total.rate" in gauges
    assert gauges.get("timeseries.samples", 0) >= 2


def test_sampler_thread_start_tick_drain():
    s = TimeSeriesSampler(interval_s=0.01, capacity=32)
    assert s.start() is True
    assert s.start() is False  # already running
    deadline = time.time() + 5.0
    while len(s) < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert len(s) >= 3
    s.drain()
    assert not s.running
    n = len(s)
    time.sleep(0.05)
    assert len(s) == n  # genuinely stopped
    s.drain()  # idempotent
    # Restartable after drain.
    assert s.start() is True
    s.drain()


def test_process_sampler_singleton_and_reset():
    a = timeseries.get_sampler()
    assert timeseries.get_sampler() is a
    fresh = TimeSeriesSampler(interval_s=0.5)
    assert timeseries.set_sampler(fresh) is fresh
    assert timeseries.get_sampler() is fresh
    timeseries.reset_sampler()
    assert timeseries.get_sampler() is not fresh


def test_sample_to_dict_is_json_able(sampler):
    import json

    reg = telemetry.get_registry()
    reg.histogram("query.wall_s").observe(0.005)
    reg.histogram("query.wall_s").observe(-1.0)  # the None bucket
    sampler.tick()
    doc = sampler.snapshot()
    text = json.dumps(doc)
    assert "-inf" in text  # the non-positive bucket key serializes
    assert doc["samples"][-1]["histograms"]["query.wall_s"]["count"] >= 2
