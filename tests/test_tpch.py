"""TPC-H q1-q22: 3-way correctness (rules on == rules off == pandas
oracle) — the reference pins all TPC-H queries through its plan layer
(`index/serde/package.scala:46-49`); here they run end to end."""

import os

import pandas as pd
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import Hyperspace, HyperspaceConf, HyperspaceSession
from hyperspace_tpu.tpch import QUERIES, generate
from hyperspace_tpu.tpch.queries import create_indexes, normalize_result


@pytest.fixture(scope="module")
def tpch_env(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpch")
    paths = generate(str(root / "data"), scale=0.3)
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(root / "wh"),
        "spark.hyperspace.index.num.buckets": "8"}))
    hs = Hyperspace(sess)
    dfs = {name: sess.read_parquet(path) for name, path in paths.items()}
    create_indexes(hs, dfs)
    pdfs = {name: pq.read_table(
        os.path.join(path, "part-0.parquet")).to_pandas()
        for name, path in paths.items()}
    return sess, dfs, pdfs


_norm = normalize_result


@pytest.mark.parametrize("name", list(QUERIES))
def test_query_correctness_rules_on_off_vs_pandas(tpch_env, name):
    sess, dfs, pdfs = tpch_env
    build, oracle = QUERIES[name]
    expected = oracle(pdfs)
    assert len(expected) > 0, f"{name}: oracle returned no rows"

    sess.enable_hyperspace()
    try:
        got_on = build(dfs).to_pandas()
    finally:
        sess.disable_hyperspace()
    got_off = build(dfs).to_pandas()

    for got, tag in ((got_on, "rules-on"), (got_off, "rules-off")):
        assert list(got.columns) == list(expected.columns), (
            name, tag, got.columns, expected.columns)
        pd.testing.assert_frame_equal(
            _norm(got), _norm(expected), check_dtype=False,
            check_exact=False, rtol=1e-6, atol=1e-9)
