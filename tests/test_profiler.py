"""Sampling profiler + triggered device capture
(telemetry/profiler.py): sampler lifecycle and export shapes, the
self-exclusion rule, bounded sampling cost, triggered-capture
atomicity / keep-N pruning / rate limiting (device_trace stubbed —
the capture plumbing is what's under test, not jax), the /profile +
/critpath endpoint round-trips, and the serving chaos run with the
profiler ON."""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from contextlib import contextmanager

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from hyperspace_tpu import telemetry
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.plan.expr import col, lit
from hyperspace_tpu.telemetry import flight, ops_server, profiler

from chaos import canonical, run_chaos


def _counter(name):
    return telemetry.get_registry().counters_dict().get(name, 0)


@pytest.fixture
def stopped_profiler():
    """Guarantee the process singleton is stopped (and capture rate
    state cleared) after the test, whatever happened inside."""
    yield
    profiler.stop_profiler()
    with profiler._capture_lock:
        profiler._last_capture_t = None


@pytest.fixture
def busy_thread():
    """A thread with a recognizable stack for the sampler to find."""
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(i * i for i in range(500))
            time.sleep(0.001)

    t = threading.Thread(target=spin, name="busy-probe", daemon=True)
    t.start()
    yield t
    stop.set()
    t.join(5)


# ---------------------------------------------------------------------------
# Sampler lifecycle + exports
# ---------------------------------------------------------------------------


def test_sampler_lifecycle_and_exports(stopped_profiler, busy_thread):
    p = profiler.SamplingProfiler(hz=100)
    assert not p.running
    p.start()
    assert p.running
    p.start()  # idempotent
    time.sleep(0.35)
    p.drain()
    assert not p.running
    assert p.ticks > 5
    assert p.samples > 0

    snap = p.snapshot()
    assert snap and all(isinstance(k, tuple) and n > 0
                        for k, n in snap.items())
    # the sampler never profiles itself
    assert not any(label.startswith(profiler.__name__ + ":")
                   for stack in snap for label in stack)

    total = sum(snap.values())
    mods = p.by_module()
    assert sum(m["samples"] for m in mods) == total
    assert all(0 <= m["share"] <= 1 for m in mods)
    funcs = p.by_function(top=5)
    assert len(funcs) <= 5

    # collapsed-stack text: `a;b;c N` per line (flamegraph.pl input)
    collapsed = p.collapsed()
    for line in collapsed.strip().splitlines():
        assert re.fullmatch(r"\S.*? \d+", line), line
    # nested flamegraph: root counts every sample, children bounded
    flame = p.flamegraph()
    assert flame["name"] == "all" and flame["value"] == total
    assert sum(c["value"] for c in flame.get("children", [])) <= total

    p.reset()
    assert p.samples == 0 and p.snapshot() == {}


def test_sampling_cost_is_bounded(stopped_profiler, busy_thread):
    """The continuous-profiling promise in microcosm: the sampler's
    own measured loop cost over a real window is a small fraction of
    that window (the full closed-loop QPS gate lives in
    bench_regress.py --serve)."""
    cost0 = _counter("profiler.sample.seconds")
    samples0 = _counter("profiler.samples")
    p = profiler.start_profiler(hz=50)
    time.sleep(0.5)
    profiler.stop_profiler()
    assert not p.running
    assert _counter("profiler.samples") > samples0
    assert _counter("profiler.sample.seconds") - cost0 < 0.1


def test_process_singleton(stopped_profiler):
    p1 = profiler.start_profiler(hz=31)
    p2 = profiler.start_profiler(hz=7)  # second start keeps the first
    assert p1 is p2 and p2.hz == 31
    assert profiler.get_profiler() is p1
    profiler.stop_profiler()
    assert not p1.running


def test_atexit_stop_is_safe_and_idempotent(stopped_profiler):
    p = profiler.start_profiler(hz=50)
    profiler._atexit_stop()   # what interpreter shutdown runs
    assert not p.running
    profiler._atexit_stop()   # and again, after everything stopped
    assert not p.running


def test_configure_respects_enabled_knob(stopped_profiler, tmp_path):
    off = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh")})
    p = profiler.configure(off)  # default: enabled=false
    assert p is None or not p.running

    on = HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "spark.hyperspace.telemetry.profiler.enabled": "true",
        "spark.hyperspace.telemetry.profiler.hz": "43",
    })
    p = profiler.configure(on)
    assert p is not None and p.running and p.hz == 43


# ---------------------------------------------------------------------------
# Triggered device capture (device_trace stubbed)
# ---------------------------------------------------------------------------


def _capture_conf(tmp_path, **extra):
    conf = {
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "spark.hyperspace.telemetry.slowlog.dir": str(tmp_path / "sl"),
        "spark.hyperspace.telemetry.profiler.capture.seconds": "0.01",
        "spark.hyperspace.telemetry.profiler.capture.min.interval."
        "seconds": "0",
    }
    conf.update({k: str(v) for k, v in extra.items()})
    return HyperspaceConf(conf)


@pytest.fixture
def stub_trace(monkeypatch):
    """Replace the jax seam with a stub that writes a marker file —
    the capture plumbing (tmp dir, atomic rename, pruning, counters)
    is what's under test."""
    traced = []

    @contextmanager
    def fake_trace(path):
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "trace.marker"), "w") as f:
            f.write("x")
        traced.append(path)
        yield

    monkeypatch.setattr(profiler, "device_trace", fake_trace)
    return traced


def _wait_done(paths, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        recent = {c["path"]: c["state"]
                  for c in profiler.recent_captures(32)}
        if all(recent.get(p) in ("done", "error") for p in paths):
            return recent
        time.sleep(0.02)
    raise AssertionError(f"captures never settled: {recent}")


def test_capture_disabled_returns_none(tmp_path, stopped_profiler):
    conf = _capture_conf(tmp_path)
    conf.set("spark.hyperspace.telemetry.profiler.capture.seconds",
             "0")
    assert profiler.request_capture(conf) is None
    assert profiler.maybe_capture_on_burn(conf, 5.0) is None


def test_triggered_capture_atomic_and_pruned(tmp_path, stub_trace,
                                             stopped_profiler):
    conf = _capture_conf(
        tmp_path, **{"spark.hyperspace.telemetry.profiler.capture."
                     "keep": "2"})
    captures0 = _counter("profiler.captures")
    paths = []
    for i in range(4):
        target = profiler.request_capture(conf, reason=f"manual-{i}")
        assert target is not None
        paths.append(target)
        _wait_done([target])
    states = _wait_done(paths)
    assert all(states[p] == "done" for p in paths)
    assert _counter("profiler.captures") == captures0 + 4

    entries = os.listdir(conf.slowlog_dir)
    kept = [e for e in entries if e.startswith("profile-")]
    # keep-N pruned to the newest 2, no half-written .tmp survives
    assert len(kept) == 2
    assert not any(e.endswith(".tmp") for e in entries)
    assert sorted(os.path.join(conf.slowlog_dir, e) for e in kept) == \
        sorted(paths[-2:])
    for e in kept:
        assert os.path.exists(os.path.join(conf.slowlog_dir, e,
                                           "trace.marker"))


def test_capture_rate_limited(tmp_path, stub_trace, stopped_profiler):
    conf = _capture_conf(
        tmp_path, **{"spark.hyperspace.telemetry.profiler.capture."
                     "min.interval.seconds": "3600"})
    with profiler._capture_lock:
        profiler._last_capture_t = None
    first = profiler.request_capture(conf, reason="first")
    assert first is not None
    assert profiler.request_capture(conf, reason="too-soon") is None
    _wait_done([first])


def test_burn_hook_fires_only_above_one(tmp_path, stub_trace,
                                        stopped_profiler):
    conf = _capture_conf(tmp_path)
    assert profiler.maybe_capture_on_burn(conf, None) is None
    assert profiler.maybe_capture_on_burn(conf, 0.7) is None
    assert profiler.maybe_capture_on_burn(conf, 1.0) is None
    target = profiler.maybe_capture_on_burn(conf, 2.5)
    assert target is not None
    entry = profiler.recent_captures()[-1]
    assert entry["reason"] == "slo-burn:2.50"
    _wait_done([target])


def test_capture_error_counted_and_tmp_cleaned(tmp_path, monkeypatch,
                                               stopped_profiler):
    @contextmanager
    def broken_trace(path):
        os.makedirs(path, exist_ok=True)
        raise RuntimeError("no profiler backend")
        yield  # pragma: no cover

    monkeypatch.setattr(profiler, "device_trace", broken_trace)
    errors0 = _counter("profiler.capture_errors")
    conf = _capture_conf(tmp_path)
    target = profiler.request_capture(conf, reason="doomed")
    assert target is not None
    states = _wait_done([target])
    assert states[target] == "error"
    assert _counter("profiler.capture_errors") == errors0 + 1
    assert not os.path.exists(target)
    assert not os.path.exists(target + ".tmp")


def test_slowlog_dump_embeds_capture_path(tmp_path, stub_trace,
                                          stopped_profiler):
    """A slow query's dump carries its own anatomy AND the device
    profile it triggered."""
    rng = np.random.default_rng(9)
    data = tmp_path / "data"
    data.mkdir()
    pq.write_table(pa.table({
        "a": rng.integers(0, 100, 2000).astype(np.int64),
    }), str(data / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
        "spark.hyperspace.telemetry.slowlog.seconds": "0.000001",
        "spark.hyperspace.telemetry.slowlog.dir": str(tmp_path / "sl"),
        "spark.hyperspace.telemetry.profiler.capture.seconds": "0.01",
        "spark.hyperspace.telemetry.profiler.capture.min.interval."
        "seconds": "0",
    }))
    sess.read_parquet(str(data)).filter(col("a") > lit(10)).collect()
    # Dumps ride the flight recorder's background writer lane; flush
    # it before reading (the dir itself is created by the lane job).
    flight.get_recorder().drain()
    dumps = [f for f in os.listdir(tmp_path / "sl")
             if f.endswith(".json")]
    assert dumps
    with open(tmp_path / "sl" / sorted(dumps)[-1]) as f:
        doc = json.load(f)
    assert "critical_path" in doc
    assert abs(doc["critical_path"]["sum_s"]
               - doc["critical_path"]["wall_s"]) <= 1e-4
    assert doc["device_profile"].startswith(str(tmp_path / "sl"))
    _wait_done([doc["device_profile"]])


# ---------------------------------------------------------------------------
# Endpoint round-trips
# ---------------------------------------------------------------------------


@pytest.fixture
def server():
    srv = ops_server.start_server(port=0)
    yield srv
    ops_server.stop_server()


def _get(srv, path):
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}", timeout=10)
    except urllib.error.HTTPError as exc:
        resp = exc
    with resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode("utf-8")


def test_profile_endpoint_round_trip(server, stopped_profiler,
                                     busy_thread):
    profiler.start_profiler(hz=97)
    time.sleep(0.25)
    status, ctype, body = _get(server, "/profile")
    assert status == 200 and ctype == "application/json"
    doc = json.loads(body)
    assert doc["enabled"] is True and doc["hz"] == 97
    assert doc["samples"] > 0 and doc["flamegraph"]["value"] >= 0
    assert isinstance(doc["captures"], list)

    status, ctype, text = _get(server, "/profile?format=collapsed")
    assert status == 200 and ctype.startswith("text/plain")
    assert text == "" or re.fullmatch(
        r"\S.*? \d+", text.strip().splitlines()[0])

    profiler.stop_profiler()
    status, _ctype, body = _get(server, "/profile")
    assert json.loads(body)["enabled"] is False


def test_critpath_endpoint_round_trip(server, tmp_path):
    rng = np.random.default_rng(4)
    data = tmp_path / "data"
    data.mkdir()
    pq.write_table(pa.table({
        "a": rng.integers(0, 100, 2000).astype(np.int64),
    }), str(data / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
    }))
    sess.read_parquet(str(data)).filter(col("a") > lit(50)).collect()

    status, ctype, body = _get(server, "/critpath")
    assert status == 200 and ctype == "application/json"
    doc = json.loads(body)
    from hyperspace_tpu.telemetry.critical_path import SEGMENTS
    assert set(doc["window"]["shares"]) == set(SEGMENTS)
    assert doc["recent"], "the served query's stamp must appear"
    cp = doc["recent"][-1]["critical_path"]
    assert abs(cp["sum_s"] - cp["wall_s"]) <= 1e-4
    assert doc["totals"]["critpath.queries"] >= 1

    status, _ctype, body = _get(server, "/nope")
    assert status == 404 and "/critpath" in body and "/profile" in body


# ---------------------------------------------------------------------------
# Chaos with the profiler ON: visibility must not cost liveness
# ---------------------------------------------------------------------------


def test_chaos_run_with_profiler_on(tmp_path, stopped_profiler):
    rng = np.random.default_rng(11)
    n = 20_000
    facts = tmp_path / "facts"
    facts.mkdir()
    pq.write_table(pa.table({
        "k": rng.integers(0, 500, n).astype(np.int64),
        "g": rng.integers(0, 16, n).astype(np.int64),
        "v": rng.random(n).astype(np.float64),
    }), str(facts / "part-0.parquet"))
    sess = HyperspaceSession(HyperspaceConf({
        "hyperspace.warehouse.dir": str(tmp_path / "wh"),
    }))
    fact = sess.read_parquet(str(facts))
    workload = [
        ("filter", fact.filter(col("k") > lit(250))),
        ("agg", fact.group_by("g").agg(("sum", "v", "sv"))),
        ("proj", fact.filter(col("g") == lit(3)).select("k", "v")),
    ]
    expected = {name: canonical(df.collect()) for name, df in workload}

    profiler.start_profiler(hz=67)
    try:
        report = run_chaos(workload, expected, clients=6,
                           total_queries=90)
    finally:
        profiler.stop_profiler()

    assert report.stuck_threads == [], report.summary()
    assert report.mismatches == [], report.summary()
    assert report.outcomes["ok"] == 90, report.summary()
    # the sampler watched the whole run and every ok query got stamped
    p = profiler.get_profiler()
    assert p is not None and p.samples > 0
    stamped = [m for m in report.success_metrics
               if getattr(m, "critical_path", None) is not None]
    assert len(stamped) == len(report.success_metrics)
    for qm in stamped:
        cp = qm.critical_path
        assert abs(cp["sum_s"] - cp["wall_s"]) <= 1e-4
