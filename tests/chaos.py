"""Chaos harness for the serving plane: K client threads, a mixed
query workload, typed-outcome accounting, and deadlock detection.

Not a test module — `tests/test_serving.py` drives it. The harness is
deliberately dumb: it runs queries on plain threads and RECORDS what
happened; every invariant (no deadlock, budget respected, correctness,
telemetry isolation, counter/outcome agreement) is asserted by the
caller against the returned `ChaosReport`, so a failure names the
invariant, not the harness.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


def canonical(table):
    """Row-order-insensitive canonical form of an Arrow table (every
    correctness comparison here is set-of-rows equality — the engine
    guarantees deterministic CONTENT, not row order, under
    concurrency)."""
    return table.sort_by([(n, "ascending") for n in table.schema.names])


class ChaosReport:
    """Everything the chaos run observed, for the caller to assert on."""

    def __init__(self):
        self.outcomes: Dict[str, int] = {
            "ok": 0, "rejected": 0, "deadline": 0, "cancelled": 0,
            "injected": 0, "error": 0}
        self.latencies: List[float] = []
        self.mismatches: List[str] = []
        self.errors: List[str] = []
        self.success_metrics: List = []   # QueryMetrics of ok queries
        self.typed_phases: List[str] = []  # phase of each typed failure
        self.stuck_threads: List[str] = []
        self.wall_s: float = 0.0

    @property
    def total(self) -> int:
        return sum(self.outcomes.values())

    def summary(self) -> str:
        return (f"{self.total} queries in {self.wall_s:.2f}s: "
                + ", ".join(f"{k}={v}" for k, v in self.outcomes.items()
                            if v)
                + (f"; {len(self.mismatches)} mismatches"
                   if self.mismatches else ""))


def run_chaos(workload: List[Tuple[str, object]],
              expected: Dict[str, object],
              clients: int,
              total_queries: int,
              timeout_for: Optional[Callable[[int], Optional[float]]]
              = None,
              join_timeout_s: float = 120.0) -> ChaosReport:
    """Drive `total_queries` from `workload` (list of (name, DataFrame))
    across `clients` closed-loop threads. `expected` maps name ->
    canonical serial-run table (the correctness oracle).
    `timeout_for(i)` optionally assigns a per-query deadline by global
    query index. Threads that fail to join within `join_timeout_s` are
    reported in `stuck_threads` — the caller's deadlock assertion."""
    from hyperspace_tpu.exceptions import (QueryCancelledError,
                                           QueryDeadlineExceededError,
                                           QueryRejectedError,
                                           QueryServingError)
    from hyperspace_tpu.utils.faults import (InjectedPermanentError,
                                             InjectedTransientError)

    report = ChaosReport()
    next_q = [0]
    lock = threading.Lock()

    def client():
        while True:
            with lock:
                if next_q[0] >= total_queries:
                    return
                qi = next_q[0]
                next_q[0] += 1
            name, df = workload[qi % len(workload)]
            timeout = timeout_for(qi) if timeout_for is not None else None
            t0 = time.perf_counter()
            try:
                table, metrics = df.collect(with_metrics=True,
                                            timeout=timeout)
            except QueryRejectedError as exc:
                with lock:
                    report.outcomes["rejected"] += 1
                    report.typed_phases.append(exc.phase or "?")
                continue
            except QueryDeadlineExceededError as exc:
                with lock:
                    report.outcomes["deadline"] += 1
                    report.typed_phases.append(exc.phase or "?")
                continue
            except QueryCancelledError as exc:
                with lock:
                    report.outcomes["cancelled"] += 1
                    report.typed_phases.append(exc.phase or "?")
                continue
            except (InjectedTransientError, InjectedPermanentError) as exc:
                # An injected fault that escaped retry/degradation: a
                # legitimate failed query (the injector aimed past the
                # resilience layers), NOT a serving defect.
                with lock:
                    report.outcomes["injected"] += 1
                    report.errors.append(f"{name}: {exc!r}")
                continue
            except QueryServingError as exc:  # pragma: no cover
                with lock:
                    report.outcomes["error"] += 1
                    report.errors.append(f"{name}: untyped serving "
                                         f"path? {exc!r}")
                continue
            except Exception as exc:
                with lock:
                    report.outcomes["error"] += 1
                    report.errors.append(f"{name}: {exc!r}")
                continue
            wall = time.perf_counter() - t0
            ok = canonical(table).equals(expected[name])
            with lock:
                report.outcomes["ok"] += 1
                report.latencies.append(wall)
                report.success_metrics.append(metrics)
                if not ok:
                    report.mismatches.append(
                        f"{name} (query {qi}): result differs from "
                        "serial run")

    threads = [threading.Thread(target=client, name=f"chaos-{c}",
                                daemon=True)
               for c in range(clients)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    deadline_t = time.monotonic() + join_timeout_s
    for th in threads:
        th.join(timeout=max(0.0, deadline_t - time.monotonic()))
        if th.is_alive():
            report.stuck_threads.append(th.name)
    report.wall_s = time.perf_counter() - t0
    return report
