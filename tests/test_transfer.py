"""Pipelined transfer engine (`io/transfer.py`): chunked round-trip
equivalence with the plain path, in-flight byte-window enforcement,
staging-buffer reuse, fault-injected put retry, decode/link overlap on a
slow-link fake, and sorted-run output identity between the chunked and
serial build paths."""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from hyperspace_tpu.io import columnar, transfer
from hyperspace_tpu.io.transfer import Host, HostCast, TransferEngine


@pytest.fixture
def engine():
    """Install a purpose-built engine as THE process engine; restore the
    default on teardown (the engine is process-wide state)."""
    def make(**kwargs) -> TransferEngine:
        return transfer.set_engine(TransferEngine(**kwargs))

    yield make
    transfer.reset_engine()


def sample_table(n: int = 5000) -> pa.Table:
    rng = np.random.default_rng(7)
    ints = rng.integers(0, 1 << 40, n).astype(np.int64)
    return pa.table({
        "i64": ints,
        "i32": pa.array(
            np.where(np.arange(n) % 7 == 0, None,
                     rng.integers(-1000, 1000, n)).tolist(),
            type=pa.int32()),
        "f64": pa.array(
            np.where(np.arange(n) % 5 == 0, None, rng.random(n)).tolist(),
            type=pa.float64()),
        "s": pa.array([None if i % 11 == 0 else f"v{i % 97}"
                       for i in range(n)], type=pa.string()),
        "b": rng.integers(0, 2, n).astype(bool),
    })


def batch_host_view(batch):
    """{name: (data, validity)} as numpy, for value comparison."""
    out = {}
    for name, col in batch.columns.items():
        out[name] = (np.asarray(col.data),
                     None if col.validity is None
                     else np.asarray(col.validity))
    return out


class FakeDev:
    """A fake device array for fake-link engines: remembers its payload,
    completes after `latency_s` (block_until_ready waits it out)."""

    def __init__(self, arr, latency_s: float = 0.0):
        self.np = np.asarray(arr).copy()  # copy, like a real transfer
        self.nbytes = self.np.nbytes
        self.done_at = time.perf_counter() + latency_s
        self.blocked = False

    def block_until_ready(self):
        delay = self.done_at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        self.blocked = True
        return self

    def __array__(self, dtype=None):
        return self.np if dtype is None else self.np.astype(dtype)


# ---------------------------------------------------------------------------
# Chunked round-trip equivalence
# ---------------------------------------------------------------------------


def test_chunked_from_arrow_matches_plain(engine):
    table = sample_table()
    plain = columnar.from_arrow(table)  # default engine: few/no chunks
    engine(chunk_bytes=1024, inflight_bytes=8192, threads=2)
    chunked = columnar.from_arrow(table)
    assert transfer.get_engine().stats["chunks"] > len(table.column_names)

    a, b = batch_host_view(plain), batch_host_view(chunked)
    for name in a:
        np.testing.assert_array_equal(a[name][0], b[name][0])
        da, db = plain.columns[name], chunked.columns[name]
        assert np.asarray(da.data).dtype == np.asarray(db.data).dtype
        if a[name][1] is None:
            assert b[name][1] is None
        else:
            np.testing.assert_array_equal(a[name][1], b[name][1])
        if da.is_string:
            np.testing.assert_array_equal(da.dictionary, db.dictionary)
    # Arrow round trip preserves values + null masks exactly.
    assert columnar.to_arrow(chunked).equals(columnar.to_arrow(plain))
    assert columnar.to_arrow(chunked).equals(table)


def test_chunked_roundtrip_empty_and_tiny(engine):
    engine(chunk_bytes=64, inflight_bytes=256, threads=1)
    empty = sample_table(0)
    assert columnar.to_arrow(columnar.from_arrow(empty)).equals(empty)
    tiny = sample_table(3)
    assert columnar.to_arrow(columnar.from_arrow(tiny)).equals(tiny)


def test_put_chunks_concatenate_to_source(engine):
    engine(chunk_bytes=4096, inflight_bytes=1 << 20, threads=2)
    arr = np.arange(10_000, dtype=np.int64)
    parts = transfer.get_engine().put_chunks(HostCast(arr, np.uint32))
    assert len(parts) > 1
    got = np.concatenate([np.asarray(p) for p in parts])
    np.testing.assert_array_equal(got, arr.astype(np.uint32))


# ---------------------------------------------------------------------------
# In-flight byte window
# ---------------------------------------------------------------------------


def test_inflight_byte_window_enforced(engine):
    outstanding = []
    lock = threading.Lock()
    max_seen = [0]

    def slow_put(arr, device):
        dev = FakeDev(arr, latency_s=0.002)
        with lock:
            outstanding.append(dev)
            live = sum(d.nbytes for d in outstanding if not d.blocked)
            max_seen[0] = max(max_seen[0], live)
        return dev

    window = 4096
    eng = engine(chunk_bytes=1024, inflight_bytes=window, threads=2,
                 put_fn=slow_put)
    arr = np.arange(8192, dtype=np.int8)  # 8 chunks of 1 KiB
    parts = eng.put_chunks(arr)
    assert len(parts) == 8
    assert max_seen[0] <= window
    assert eng.stats["window_waits"] > 0
    got = np.concatenate([p.np for p in parts])
    np.testing.assert_array_equal(got, arr)


# ---------------------------------------------------------------------------
# Staging-buffer reuse
# ---------------------------------------------------------------------------


def test_staging_buffers_reused_not_rematerialized(engine, monkeypatch):
    # Drop the staging floor so test-size chunks hit the buffer pool.
    # The fake link COPIES (like a real accelerator link); on the bare
    # CPU backend staging is disabled — see the test below.
    monkeypatch.setattr(transfer, "_STAGING_MIN_BYTES", 1)
    eng = engine(chunk_bytes=4096, inflight_bytes=8192, threads=2,
                 put_fn=lambda arr, device: FakeDev(arr))
    arr = np.arange(64_000, dtype=np.int64)  # ~63 int32 chunks
    parts = eng.put_chunks(HostCast(arr, np.int32))
    got = np.concatenate([p.np for p in parts])
    np.testing.assert_array_equal(got, arr.astype(np.int32))
    stats = eng.stats
    assert stats["staging_reused"] > 20, stats
    # Double-buffering needs only a handful of buffers, not one per chunk.
    assert stats["staging_allocated"] <= 2 * eng.threads + 2, stats
    assert stats["staging_allocated"] + stats["staging_reused"] \
        == len(parts)


def test_staging_disabled_on_cpu_aliasing_backend(engine):
    # The CPU PJRT client may ZERO-COPY aligned host buffers into the
    # "device" array; rewriting a reused staging buffer would then
    # corrupt already-placed chunks, so the engine must refuse staging
    # on the cpu platform — and values must stay correct without it.
    eng = engine(chunk_bytes=4096, inflight_bytes=1 << 20, threads=2)
    assert eng._staging_ok() is False  # conftest forces the cpu backend
    arr = np.arange(100_000, dtype=np.int64)
    parts = eng.put_chunks(HostCast(arr, np.int32))
    got = np.concatenate([np.asarray(p) for p in parts])
    np.testing.assert_array_equal(got, arr.astype(np.int32))
    assert eng.stats["staging_reused"] == 0
    assert eng.stats["staging_allocated"] == 0


# ---------------------------------------------------------------------------
# Fault-injected transient put
# ---------------------------------------------------------------------------


def test_transient_put_retries_preserving_chunk_order(engine,
                                                      fault_injector):
    from hyperspace_tpu import telemetry
    from hyperspace_tpu.utils.faults import FaultRule

    eng = engine(chunk_bytes=1024, inflight_bytes=8192, threads=2)
    inj = fault_injector(FaultRule("transfer.put", kind="transient",
                                   nth=3, times=2))
    retries_before = telemetry.get_registry().counter("io.retries").value
    arr = np.arange(4096, dtype=np.int16)  # 4 chunks
    parts = eng.put_chunks(arr)
    got = np.concatenate([np.asarray(p) for p in parts])
    np.testing.assert_array_equal(got, arr)  # order survived the retries
    assert inj.fired("transfer.put") == 2
    assert telemetry.get_registry().counter("io.retries").value \
        == retries_before + 2


def test_permanent_put_raises(engine, fault_injector):
    from hyperspace_tpu.utils.faults import (FaultRule,
                                             InjectedPermanentError)

    eng = engine(chunk_bytes=1 << 20, inflight_bytes=1 << 22)
    fault_injector(FaultRule("transfer.put", kind="permanent"))
    with pytest.raises(InjectedPermanentError):
        eng.put(np.arange(10))


# ---------------------------------------------------------------------------
# Overlap: decode + link pipelining beats the serial sum
# ---------------------------------------------------------------------------


def test_slow_link_overlap_beats_serial(engine):
    from hyperspace_tpu import telemetry

    put_s = 0.01
    decode_s = 0.02
    n_jobs = 6

    def slow_put(arr, device):
        time.sleep(put_s)  # a dispatch-blocking (tunneled) link
        return FakeDev(arr)

    eng = engine(chunk_bytes=1 << 20, inflight_bytes=1 << 22, threads=2,
                 put_fn=slow_put)

    def job():
        time.sleep(decode_s)  # Arrow decode stage
        return {"data": np.arange(256, dtype=np.int64)}

    saved_before = telemetry.get_registry().counter(
        "transfer.overlap_saved_seconds").value
    t0 = time.perf_counter()
    results = eng.put_group([job] * n_jobs)
    wall = time.perf_counter() - t0
    serial = n_jobs * (decode_s + put_s)
    assert wall < 0.8 * serial, (wall, serial)
    assert len(results) == n_jobs
    for r in results:
        np.testing.assert_array_equal(r["data"].np,
                                      np.arange(256, dtype=np.int64))
    assert telemetry.get_registry().counter(
        "transfer.overlap_saved_seconds").value > saved_before


def test_put_group_host_marker_passthrough(engine):
    eng = engine()
    dictionary = np.array(["a", "b"])
    [res] = eng.put_group([lambda: {"data": np.arange(4),
                                    "dictionary": Host(dictionary),
                                    "n": 4, "none": None}])
    assert res["dictionary"] is dictionary
    assert res["n"] == 4 and res["none"] is None
    assert not isinstance(res["data"], np.ndarray)  # placed on device


# ---------------------------------------------------------------------------
# Telemetry & counters
# ---------------------------------------------------------------------------


def test_link_chunk_counters_and_d2h(engine):
    from hyperspace_tpu import telemetry

    reg = telemetry.get_registry()
    h2d_chunks0 = reg.counter("link.h2d.chunks").value
    d2h_chunks0 = reg.counter("link.d2h.chunks").value
    eng = engine(chunk_bytes=1024, inflight_bytes=8192, threads=2)
    dev = eng.put(np.arange(1024, dtype=np.int64))  # 8 chunks
    assert reg.counter("link.h2d.chunks").value >= h2d_chunks0 + 8
    np.testing.assert_array_equal(eng.fetch(dev),
                                  np.arange(1024, dtype=np.int64))
    assert reg.counter("link.d2h.chunks").value > d2h_chunks0


def test_prefetch_errors_are_counted(engine):
    from hyperspace_tpu import telemetry

    class BadPrefetch:
        def copy_to_host_async(self):
            raise RuntimeError("dead DMA path")

    reg = telemetry.get_registry()
    before = reg.counter("link.d2h.prefetch_errors").value
    eng = engine()
    eng.prefetch(BadPrefetch(), np.arange(3), BadPrefetch())
    assert reg.counter("link.d2h.prefetch_errors").value == before + 2


def test_conf_knobs_configure_engine(engine):
    from hyperspace_tpu.config import HyperspaceConf

    eng = engine()
    conf = HyperspaceConf({
        "spark.hyperspace.io.transfer.chunk.bytes": "2048",
        "spark.hyperspace.io.transfer.inflight.bytes": "16384",
        "spark.hyperspace.io.transfer.threads": "3",
    })
    transfer.configure(conf)
    assert eng.chunk_bytes == 2048
    assert eng.inflight_bytes == 16384
    assert eng.threads == 3


# ---------------------------------------------------------------------------
# Build-path identity: chunked pipeline == serial path, byte for byte
# ---------------------------------------------------------------------------


def build_table(n: int = 20_000) -> pa.Table:
    rng = np.random.default_rng(11)
    return pa.table({
        "key": rng.integers(0, n // 4, n).astype(np.int64),
        "score": rng.random(n).astype(np.float64),
    })


def read_sorted_runs(path):
    from hyperspace_tpu.io import parquet as pq_io
    per_bucket = pq_io.bucket_files(str(path))
    return {b: pq_io.read_table(files)
            for b, files in sorted(per_bucket.items())}


def test_sorted_runs_identical_across_chunking(engine, tmp_path,
                                               monkeypatch):
    from hyperspace_tpu.io import builder

    table = build_table()
    # Force the DEVICE permutation lane regardless of build size so the
    # chunked D2H + pipelined writer path runs under test.
    monkeypatch.setattr(builder, "BUILD_MIN_DEVICE_ROWS", 0)
    monkeypatch.setattr(builder, "_host_lane_preferred", lambda rows: False)

    engine(chunk_bytes=1 << 26, inflight_bytes=1 << 28)  # effectively serial
    serial = builder.write_bucketed_table(table, ["key"], 8,
                                          str(tmp_path / "serial"))
    engine(chunk_bytes=16 * 1024, inflight_bytes=64 * 1024, threads=2)
    chunked = builder.write_bucketed_table(table, ["key"], 8,
                                           str(tmp_path / "chunked"))
    assert serial and chunked
    a = read_sorted_runs(tmp_path / "serial")
    b = read_sorted_runs(tmp_path / "chunked")
    assert set(a) == set(b)
    for bucket in a:
        # Same rows in the same order per bucket; the chunked path may
        # split a bucket into more run files, but their name-ordered
        # concatenation must be identical.
        assert a[bucket].equals(b[bucket]), f"bucket {bucket} diverged"


def test_pipelined_file_build_matches_host_lane(engine, tmp_path,
                                                monkeypatch):
    import pyarrow.parquet as pq

    from hyperspace_tpu.io import builder

    table = build_table(8000)
    src = tmp_path / "src"
    src.mkdir()
    pq.write_table(table.slice(0, 3000), str(src / "part-0.parquet"))
    pq.write_table(table.slice(3000), str(src / "part-1.parquet"))
    files = [str(src / "part-0.parquet"), str(src / "part-1.parquet")]

    engine(chunk_bytes=8 * 1024, inflight_bytes=32 * 1024, threads=2)
    host = builder.write_bucketed_from_files(
        files, ["key", "score"], ["key"], 8, str(tmp_path / "host"))
    monkeypatch.setattr(builder, "_host_lane_preferred", lambda rows: False)
    dev = builder.write_bucketed_from_files(
        files, ["key", "score"], ["key"], 8, str(tmp_path / "dev"))
    assert host and dev
    a = read_sorted_runs(tmp_path / "host")
    b = read_sorted_runs(tmp_path / "dev")
    assert set(a) == set(b)
    for bucket in a:
        assert a[bucket].equals(b[bucket])
