"""Columnar substrate tests: arrow <-> device round trips, dictionary
encoding invariants, nulls, batch concat with dictionary unification."""

import numpy as np
import pyarrow as pa
import pytest

from hyperspace_tpu.io import columnar
from hyperspace_tpu.plan.schema import Schema


def sample_table():
    return pa.table({
        "i64": np.array([3, 1, 2], dtype=np.int64),
        "i32": np.array([30, 10, 20], dtype=np.int32),
        "f64": np.array([0.3, 0.1, 0.2]),
        "s": pa.array(["banana", "apple", "cherry"]),
        "b": pa.array([True, False, True]),
    })


def test_roundtrip():
    table = sample_table()
    batch = columnar.from_arrow(table)
    assert batch.num_rows == 3
    out = columnar.to_arrow(batch)
    assert out.equals(table)


def test_string_codes_order_preserving():
    batch = columnar.from_arrow(sample_table())
    col = batch.column("s")
    codes = np.asarray(col.data)
    values = col.dictionary[codes]
    # codes compare exactly like values
    assert list(np.argsort(codes)) == list(np.argsort(values))
    assert list(col.dictionary) == sorted(col.dictionary)


def test_dict_hashes_value_identity():
    """Same value in different batches (different dictionaries) must carry
    the same hash — the bucket-stability invariant."""
    t1 = pa.table({"s": pa.array(["x", "y"])})
    t2 = pa.table({"s": pa.array(["a", "y", "z"])})
    b1 = columnar.from_arrow(t1)
    b2 = columnar.from_arrow(t2)
    h1 = dict(zip(b1.column("s").dictionary,
                  zip(np.asarray(b1.column("s").dict_hashes[0]),
                      np.asarray(b1.column("s").dict_hashes[1]))))
    h2 = dict(zip(b2.column("s").dictionary,
                  zip(np.asarray(b2.column("s").dict_hashes[0]),
                      np.asarray(b2.column("s").dict_hashes[1]))))
    assert h1["y"] == h2["y"]


def test_nulls_roundtrip():
    table = pa.table({
        "x": pa.array([1, None, 3], type=pa.int64()),
        "s": pa.array(["a", None, "c"]),
    })
    batch = columnar.from_arrow(table)
    assert batch.column("x").validity is not None
    out = columnar.to_arrow(batch)
    assert out.column("x").null_count == 1
    assert out.column("s").null_count == 1
    assert out.column("x").to_pylist() == [1, None, 3]
    assert out.column("s").to_pylist() == ["a", None, "c"]


def test_take():
    import jax.numpy as jnp
    batch = columnar.from_arrow(sample_table())
    taken = batch.take(jnp.asarray([2, 0]))
    out = columnar.to_arrow(taken)
    assert out.column("i64").to_pylist() == [2, 3]
    assert out.column("s").to_pylist() == ["cherry", "banana"]


def test_concat_unifies_dictionaries():
    t1 = pa.table({"s": pa.array(["m", "a"]), "v": np.array([1, 2], dtype=np.int64)})
    t2 = pa.table({"s": pa.array(["z", "m"]), "v": np.array([3, 4], dtype=np.int64)})
    merged = columnar.concat_batches(
        [columnar.from_arrow(t1), columnar.from_arrow(t2)])
    out = columnar.to_arrow(merged)
    assert out.column("s").to_pylist() == ["m", "a", "z", "m"]
    col = merged.column("s")
    codes = np.asarray(col.data)
    # codes still order-preserving after unification
    assert (col.dictionary[codes] == np.array(["m", "a", "z", "m"])).all()


def test_select_case_insensitive():
    batch = columnar.from_arrow(sample_table())
    sub = batch.select(["I64", "S"])
    assert sub.schema.names == ["i64", "s"]


def test_arrow_encode_matches_reference_impl():
    """Production arrow-native encoding must agree with the numpy reference
    implementation on codes, dictionary order, and hashes."""
    from hyperspace_tpu.io.columnar import (_encode_strings,
                                            _encode_strings_arrow)
    values = ["pear", "apple", None, "pear", "", "zebra", "apple"]
    arr = pa.array(values, type=pa.string())
    codes_a, dict_a, hashes_a, validity_a = _encode_strings_arrow(arr)
    codes_r, dict_r, hashes_r, mask_r = _encode_strings(
        np.array(values, dtype=object))
    assert list(dict_a) == list(dict_r)
    assert list(codes_a) == list(codes_r)
    assert list(hashes_a) == list(hashes_r)
    assert list(validity_a) == list(mask_r)


def test_dictionary_typed_input_with_duplicates_and_nulls():
    """Dictionary-typed arrow columns with duplicate or null dictionary
    entries must be normalized (equal values -> equal codes)."""
    dict_arr = pa.DictionaryArray.from_arrays(
        pa.array([0, 1, 2, 3], type=pa.int32()),
        pa.array(["x", "x", None, "y"]))
    batch = columnar.from_arrow(pa.table({"s": dict_arr}))
    col = batch.column("s")
    codes = np.asarray(col.data)
    assert codes[0] == codes[1]  # both "x"
    assert col.validity is not None
    assert list(np.asarray(col.validity)) == [True, True, False, True]
    out = columnar.to_arrow(batch)
    assert out.column("s").to_pylist() == ["x", "x", None, "y"]


def test_multicolumn_two_lane_hash_consistency():
    """All bucket-assignment paths must agree for multi-column keys where a
    non-first column has two lanes (int64/string) — the flat-lane identity."""
    from hyperspace_tpu.io.columnar import batch_to_tree
    from hyperspace_tpu.ops.build import _tree_bucket_ids
    from hyperspace_tpu.ops.hash_partition import bucket_ids
    from hyperspace_tpu.ops.pallas.hash_kernel import hash_lanes_to_buckets
    from hyperspace_tpu.ops.build import _tree_hash_lanes

    rng = np.random.default_rng(3)
    table = pa.table({
        "a": rng.integers(0, 100, 500).astype(np.int32),
        "b": rng.integers(-2**60, 2**60, 500).astype(np.int64),
        "s": pa.array([f"v{int(x)}" for x in rng.integers(0, 30, 500)]),
    })
    batch = columnar.from_arrow(table)
    keys = ["a", "b", "s"]
    eager = np.asarray(bucket_ids(batch, keys, 16))
    tree, _ = batch_to_tree(batch)
    jnp_path = np.asarray(_tree_bucket_ids(tree, tuple(keys), 16,
                                           use_pallas=False))
    lanes = [lane for k in keys for lane in _tree_hash_lanes(tree[k])]
    pallas_path = np.asarray(hash_lanes_to_buckets(lanes, 16, interpret=True))
    assert (eager == jnp_path).all()
    assert (eager == pallas_path).all()


def test_host_and_device_builds_produce_identical_layout(tmp_path):
    """The host-lane build must write the SAME bucket layout (same rows in
    the same buckets, sorted the same) as the device program — bucket
    pruning and co-bucketed joins depend on the shared hash identity."""
    import os
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from hyperspace_tpu.io import builder

    rng = np.random.default_rng(17)
    n = 3000
    table = pa.table({
        "k": rng.integers(0, 700, n).astype(np.int64),
        "s": pa.array([None if i % 31 == 0 else "v%d" % (i % 53)
                       for i in range(n)]),
        "x": rng.standard_normal(n),
    })
    host_dir, dev_dir = str(tmp_path / "host"), str(tmp_path / "dev")
    assert n < builder.BUILD_MIN_DEVICE_ROWS
    builder.write_bucketed_table(table, ["k", "s"], 16, host_dir)
    orig = builder.BUILD_MIN_DEVICE_ROWS
    builder.BUILD_MIN_DEVICE_ROWS = 0
    try:
        builder.write_bucketed_table(table, ["k", "s"], 16, dev_dir)
    finally:
        builder.BUILD_MIN_DEVICE_ROWS = orig
    host_files = sorted(os.listdir(host_dir))
    dev_files = sorted(os.listdir(dev_dir))
    assert host_files == dev_files
    for f in host_files:
        h = pq.read_table(os.path.join(host_dir, f))
        d = pq.read_table(os.path.join(dev_dir, f))
        hk = h.column("k").to_numpy()
        dk = d.column("k").to_numpy()
        assert (hk == dk).all(), f"bucket {f}: key order differs"
        assert sorted(h.column("x").to_pylist()) == \
            sorted(d.column("x").to_pylist())


def test_read_cache_serves_and_invalidates(tmp_path):
    """The decoded-read cache serves unchanged files and MISSES when a
    file is rewritten in place (stamp mismatch) — correctness must never
    depend on cache state."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from hyperspace_tpu.io import parquet as P

    f = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"x": np.arange(5, dtype=np.int64)}), f)
    P.clear_read_cache()
    t1 = P.read_table([f])
    t2 = P.read_table([f])
    assert t2 is t1  # cache hit returns the same decoded table

    import os, time
    time.sleep(0.01)
    pq.write_table(pa.table({"x": np.arange(9, dtype=np.int64)}), f)
    t3 = P.read_table([f])
    assert t3 is not t1 and t3.num_rows == 9  # stamp changed -> fresh read

    # Column projection is part of the key.
    t4 = P.read_table([f], columns=["x"])
    assert t4.num_rows == 9
    P.clear_read_cache()
