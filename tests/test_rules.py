"""Rewrite-rule tests on hand-built plans with fabricated index entries
(reference test layer 4: `FilterIndexRuleTest`, `JoinIndexRuleTest`,
`JoinIndexRankerTest` — fabricated `IndexLogEntry`s written via a real log
manager, injectable signature provider)."""

import os

import pytest

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.constants import States
from hyperspace_tpu.engine.session import HyperspaceSession
from hyperspace_tpu.facade import Hyperspace
from hyperspace_tpu.index.log_entry import (Content, CoveringIndex,
                                            IndexLogEntry, Hdfs, Directory,
                                            LogicalPlanFingerprint,
                                            NoOpFingerprint, PlanSource,
                                            Signature, Source)
from hyperspace_tpu.index.log_manager import IndexLogManagerImpl
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import Filter, Join, Project, Scan
from hyperspace_tpu.plan.rules.filter_index import FilterIndexRule
from hyperspace_tpu.plan.rules.join_index import JoinIndexRule
from hyperspace_tpu.plan.rules.ranker import JoinIndexRanker
from hyperspace_tpu.plan.schema import Field, Schema

from fakes import TestSignatureProvider, make_entry


SCHEMA = Schema([Field("c1", "int64"), Field("c2", "int64"),
                 Field("c3", "string"), Field("c4", "int64")])


@pytest.fixture
def session(tmp_path):
    conf = HyperspaceConf({"hyperspace.warehouse.dir": str(tmp_path / "wh")})
    return HyperspaceSession(conf)


def fabricate_index(session, name, indexed, included, source_plan,
                    num_buckets=10, state=States.ACTIVE):
    """Write a fabricated ACTIVE IndexLogEntry through a real log manager
    (like the reference rule tests)."""
    manager = Hyperspace.get_context(session).index_collection_manager
    index_path = manager.path_resolver.get_index_path(name)
    provider = TestSignatureProvider()
    sig = provider.signature(source_plan)
    schema = source_plan.schema.select(indexed + included)
    entry = IndexLogEntry(
        name=name,
        derived_dataset=CoveringIndex(indexed, included, schema.to_json(),
                                      num_buckets),
        content=Content(os.path.join(index_path, "v__=0"), []),
        source=Source(PlanSource("{}", LogicalPlanFingerprint(
            [Signature(provider.name(), sig)])),
            [Hdfs(Content("", [Directory("", [], NoOpFingerprint())]))]),
        extra={})
    entry.state = state
    log_manager = IndexLogManagerImpl(index_path)
    log_id = (log_manager.get_latest_id() or -1) + 1
    assert log_manager.write_log(log_id, entry)
    manager.clear_cache()
    return entry


def base_scan(tmp_path, name="t1", schema=SCHEMA):
    root = tmp_path / name
    root.mkdir(exist_ok=True)
    (root / "f.parquet").write_text("")
    return Scan([str(root)], schema)


# -- FilterIndexRule ------------------------------------------------------


def test_filter_rule_rewrites_covered_query(session, tmp_path):
    scan = base_scan(tmp_path)
    fabricate_index(session, "fidx", ["c1"], ["c2"], scan)
    plan = Project(["c2"], Filter(col("c1") == 10, scan))
    out = FilterIndexRule(session).apply(plan)
    leaf = out.collect_leaves()[0]
    assert "fidx" in leaf.root_paths[0]
    # Filter rewrite keeps the bucket spec: unlike the reference (where a
    # spec would throttle Spark's scan parallelism), carrying it lets the
    # physical planner prune the read to the literal's hash bucket.
    assert leaf.bucket_spec is not None
    assert leaf.bucket_spec.bucket_columns == ("c1",)
    assert isinstance(out, Project) and out.columns == ["c2"]


def test_filter_rule_bare_filter(session, tmp_path):
    scan = base_scan(tmp_path)
    fabricate_index(session, "fidx", ["c1"], ["c2", "c3", "c4"], scan)
    out = FilterIndexRule(session).apply(Filter(col("c1") == 10, scan))
    assert "fidx" in out.collect_leaves()[0].root_paths[0]


def test_filter_rule_cost_based_ranking(session, tmp_path):
    """With several covering indexes, the CHEAPEST one (smallest on-disk
    data) is chosen — exceeding the reference's first-wins placeholder
    (`FilterIndexRule.scala:222-228`)."""
    scan = base_scan(tmp_path)
    wide = fabricate_index(session, "aWide", ["c1"], ["c2", "c3", "c4"],
                           scan)
    narrow = fabricate_index(session, "zNarrow", ["c1"], ["c2"], scan)
    for entry, nbytes in ((wide, 4096), (narrow, 64)):
        os.makedirs(entry.content.root, exist_ok=True)
        with open(os.path.join(entry.content.root, "part-0.parquet"),
                  "wb") as f:
            f.write(b"x" * nbytes)
    plan = Project(["c2"], Filter(col("c1") == 10, scan))
    out = FilterIndexRule(session).apply(plan)
    # First-wins would pick aWide (listed first); cost picks zNarrow.
    assert "zNarrow" in out.collect_leaves()[0].root_paths[0]


def test_filter_rule_ranking_uses_stamped_stats_no_fs(session, tmp_path,
                                                      monkeypatch):
    """Entries carrying build-time stats (`extra.stats`) are ranked from
    metadata ONLY — zero filesystem calls on the rank path (round-4
    review item 6). The directory walk is only a fallback for entries
    predating the stamp."""
    import hyperspace_tpu.plan.rules.filter_index as fi
    from hyperspace_tpu.utils import file_utils

    scan = base_scan(tmp_path)
    wide = fabricate_index(session, "aWide", ["c1"], ["c2", "c3", "c4"],
                           scan)
    narrow = fabricate_index(session, "zNarrow", ["c1"], ["c2"], scan)
    # Stamp stats the way the build does; sizes contradict what any disk
    # walk would find (no data dirs exist at all).
    for entry, nbytes in ((wide, 4096), (narrow, 64)):
        entry.extra["stats"] = {"dataSizeBytes": nbytes, "rowCount": 10}

    calls = []

    def counting_walk(path):
        calls.append(path)
        return 0

    monkeypatch.setattr(file_utils, "get_directory_size", counting_walk)
    picked = fi.FilterIndexRule._rank([wide, narrow])
    assert picked.name == "zNarrow"
    assert calls == []  # metadata-only: the walk was never taken


def test_filter_rule_ranking_prefers_populated_over_missing(session,
                                                            tmp_path):
    """An index whose data root vanished out-of-band (0 bytes) must not
    win the ranking by looking free — a populated covering index beats
    it even when wider (review regression)."""
    scan = base_scan(tmp_path)
    wide = fabricate_index(session, "aWide", ["c1"], ["c2", "c3", "c4"],
                           scan)
    fabricate_index(session, "zGone", ["c1"], ["c2"], scan)  # no data dir
    os.makedirs(wide.content.root, exist_ok=True)
    with open(os.path.join(wide.content.root, "part-0.parquet"), "wb") as f:
        f.write(b"x" * 512)
    plan = Project(["c2"], Filter(col("c1") == 10, scan))
    out = FilterIndexRule(session).apply(plan)
    assert "aWide" in out.collect_leaves()[0].root_paths[0]


def test_filter_rule_ranking_bucket_tiebreak(session, tmp_path):
    """Equal cost (no data dirs on disk -> column-count fallback ties):
    MORE buckets wins — finer point-filter bucket pruning."""
    scan = base_scan(tmp_path)
    fabricate_index(session, "coarse", ["c1"], ["c2"], scan, num_buckets=4)
    fabricate_index(session, "fine", ["c1"], ["c2"], scan, num_buckets=32)
    plan = Project(["c2"], Filter(col("c1") == 10, scan))
    out = FilterIndexRule(session).apply(plan)
    leaf = out.collect_leaves()[0]
    assert "fine" in leaf.root_paths[0]
    assert leaf.bucket_spec.num_buckets == 32


def test_filter_rule_requires_first_indexed_column(session, tmp_path):
    scan = base_scan(tmp_path)
    fabricate_index(session, "fidx", ["c1", "c2"], ["c3"], scan)
    # filter on c2 only: first indexed column c1 not referenced -> no rewrite
    plan = Project(["c3"], Filter(col("c2") == 10, scan))
    out = FilterIndexRule(session).apply(plan)
    assert out.collect_leaves()[0].root_paths == scan.root_paths


def test_filter_rule_requires_coverage(session, tmp_path):
    scan = base_scan(tmp_path)
    fabricate_index(session, "fidx", ["c1"], ["c2"], scan)
    # c4 not covered -> no rewrite
    plan = Project(["c4"], Filter(col("c1") == 10, scan))
    out = FilterIndexRule(session).apply(plan)
    assert out.collect_leaves()[0].root_paths == scan.root_paths


def test_filter_rule_signature_mismatch(session, tmp_path):
    scan = base_scan(tmp_path, "t1")
    other = base_scan(tmp_path, "other")
    fabricate_index(session, "fidx", ["c1"], ["c2"], other)
    plan = Project(["c2"], Filter(col("c1") == 10, scan))
    out = FilterIndexRule(session).apply(plan)
    assert out.collect_leaves()[0].root_paths == scan.root_paths


def test_filter_rule_ignores_non_active(session, tmp_path):
    scan = base_scan(tmp_path)
    fabricate_index(session, "fidx", ["c1"], ["c2"], scan,
                    state=States.DELETED)
    plan = Project(["c2"], Filter(col("c1") == 10, scan))
    out = FilterIndexRule(session).apply(plan)
    assert out.collect_leaves()[0].root_paths == scan.root_paths


# -- JoinIndexRule --------------------------------------------------------


def join_plan(tmp_path, cond=None):
    left = base_scan(tmp_path, "tl")
    right = base_scan(tmp_path, "tr",
                      Schema([Field("d1", "int64"), Field("d2", "int64")]))
    return Join(left, right, cond or (col("c1") == col("d1")))


def test_join_rule_rewrites_both_sides(session, tmp_path):
    plan = join_plan(tmp_path)
    fabricate_index(session, "lidx", ["c1"],
                    ["c2", "c3", "c4"], plan.left, num_buckets=10)
    fabricate_index(session, "ridx", ["d1"], ["d2"], plan.right,
                    num_buckets=10)
    out = JoinIndexRule(session).apply(plan)
    leaves = out.collect_leaves()
    assert "lidx" in leaves[0].root_paths[0]
    assert "ridx" in leaves[1].root_paths[0]
    # join rewrite sets the bucket spec -> planner elides exchange+sort
    assert leaves[0].bucket_spec is not None
    assert leaves[0].bucket_spec.num_buckets == 10
    assert leaves[1].bucket_spec.bucket_columns == ("d1",)


def test_join_rule_requires_indexes_on_both_sides(session, tmp_path):
    plan = join_plan(tmp_path)
    fabricate_index(session, "lidx", ["c1"], ["c2", "c3", "c4"], plan.left)
    out = JoinIndexRule(session).apply(plan)
    assert out.collect_leaves()[0].root_paths == plan.left.root_paths


def test_join_rule_requires_set_equal_join_cols(session, tmp_path):
    plan = join_plan(tmp_path)
    # index on (c1, c2) but join only on c1 -> indexed cols not set-equal
    fabricate_index(session, "lidx", ["c1", "c2"], ["c3", "c4"], plan.left)
    fabricate_index(session, "ridx", ["d1"], ["d2"], plan.right)
    out = JoinIndexRule(session).apply(plan)
    assert out.collect_leaves()[0].root_paths == plan.left.root_paths


def test_join_rule_rejects_non_equi(session, tmp_path):
    plan = join_plan(tmp_path, cond=(col("c1") > col("d1")))
    fabricate_index(session, "lidx", ["c1"], ["c2", "c3", "c4"], plan.left)
    fabricate_index(session, "ridx", ["d1"], ["d2"], plan.right)
    out = JoinIndexRule(session).apply(plan)
    assert out.collect_leaves()[0].root_paths == plan.left.root_paths


def test_join_rule_multi_key_order_compatibility(session, tmp_path):
    left = base_scan(tmp_path, "tl")
    right = base_scan(tmp_path, "tr",
                      Schema([Field("d1", "int64"), Field("d2", "int64")]))
    cond = (col("c1") == col("d1")) & (col("c2") == col("d2"))
    plan = Join(left, right, cond)
    # right index has REVERSED key order -> incompatible bucket layout
    fabricate_index(session, "lidx", ["c1", "c2"], ["c3", "c4"], left)
    fabricate_index(session, "ridx", ["d2", "d1"], [], right)
    out = JoinIndexRule(session).apply(plan)
    assert out.collect_leaves()[0].root_paths == left.root_paths
    # matching order -> rewrite fires
    fabricate_index(session, "ridx2", ["d1", "d2"], [], right)
    out2 = JoinIndexRule(session).apply(plan)
    assert "lidx" in out2.collect_leaves()[0].root_paths[0]
    assert "ridx2" in out2.collect_leaves()[1].root_paths[0]


def test_join_rule_nonlinear_side_rejected(session, tmp_path):
    inner = join_plan(tmp_path)
    right2 = base_scan(tmp_path, "t3", Schema([Field("e1", "int64")]))
    outer = Join(inner, right2, col("c1") == col("e1"))
    fabricate_index(session, "lidx", ["c1"], ["c2", "c3", "c4"], inner.left)
    fabricate_index(session, "eidx", ["e1"], [], right2)
    out = JoinIndexRule(session).apply(outer)
    # the non-linear left side blocks the outer rewrite; the INNER join may
    # still be rewritten independently (it is linear), so just assert the
    # outer right side (linear, indexed) wasn't paired with the bad left
    assert isinstance(out, Join)


# -- Ranker ---------------------------------------------------------------


def test_ranker_prefers_equal_buckets_then_larger():
    a100, b100 = make_entry(num_buckets=100), make_entry(num_buckets=100)
    a200, b200 = make_entry(num_buckets=200), make_entry(num_buckets=200)
    a50 = make_entry(num_buckets=50)
    ranked = JoinIndexRanker.rank([(a100, a50), (a100, b100),
                                   (a200, b200), (a100, a200)])
    assert (ranked[0][0].num_buckets, ranked[0][1].num_buckets) == (200, 200)
    assert (ranked[1][0].num_buckets, ranked[1][1].num_buckets) == (100, 100)
    # non-equal pairs last, larger total first
    assert ranked[2][0].num_buckets + ranked[2][1].num_buckets >= \
        ranked[3][0].num_buckets + ranked[3][1].num_buckets


def test_rule_order_join_before_filter(session, tmp_path):
    """Session plugs JoinIndexRule before FilterIndexRule (reference
    `package.scala:23-34`)."""
    session.enable_hyperspace()
    from hyperspace_tpu.plan.rules.join_index import JoinIndexRule as J
    from hyperspace_tpu.plan.rules.filter_index import FilterIndexRule as F
    assert isinstance(session._rules[0], J)
    assert isinstance(session._rules[1], F)
    session.disable_hyperspace()
    assert session._rules == []
    assert not session.is_hyperspace_enabled
