"""Filesystem op-log manager tests (reference `IndexLogManagerImplTest`)."""

import os
import threading

from hyperspace_tpu import constants
from hyperspace_tpu.index.log_manager import IndexLogManagerImpl

from fakes import make_entry


def test_write_and_get_log(tmp_path):
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    entry = make_entry(state="CREATING")
    assert mgr.write_log(0, entry)
    read = mgr.get_log(0)
    assert read is not None
    assert read.state == "CREATING"
    assert read.id == 0
    assert mgr.get_log(5) is None


def test_write_log_refuses_existing_id(tmp_path):
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    assert mgr.write_log(0, make_entry(state="CREATING"))
    assert not mgr.write_log(0, make_entry(state="ACTIVE"))
    assert mgr.get_log(0).state == "CREATING"


def test_occ_single_winner_concurrent(tmp_path):
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    outcomes = []

    def attempt(i):
        outcomes.append(mgr.write_log(7, make_entry(state=f"S{i}")))

    threads = [threading.Thread(target=attempt, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(outcomes) == 1


def test_latest_id_and_log(tmp_path):
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    assert mgr.get_latest_id() is None
    assert mgr.get_latest_log() is None
    for i, state in enumerate(["CREATING", "ACTIVE", "REFRESHING"]):
        mgr.write_log(i, make_entry(state=state))
    assert mgr.get_latest_id() == 2
    assert mgr.get_latest_log().state == "REFRESHING"


def test_latest_stable_log_scan_fallback(tmp_path):
    """Without a latestStable file, scan ids downward for a stable state
    (reference `IndexLogManager.scala:91-110`)."""
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    mgr.write_log(0, make_entry(state="CREATING"))
    mgr.write_log(1, make_entry(state="ACTIVE"))
    mgr.write_log(2, make_entry(state="REFRESHING"))
    stable = mgr.get_latest_stable_log()
    assert stable is not None
    assert stable.state == "ACTIVE"
    assert stable.id == 1


def test_create_and_delete_latest_stable(tmp_path):
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    mgr.write_log(0, make_entry(state="ACTIVE"))
    assert mgr.create_latest_stable_log(0)
    stable_path = os.path.join(str(tmp_path / "idx"), constants.HYPERSPACE_LOG,
                               constants.LATEST_STABLE_LOG)
    assert os.path.exists(stable_path)
    assert mgr.get_latest_stable_log().state == "ACTIVE"
    assert mgr.delete_latest_stable_log()
    assert not os.path.exists(stable_path)
    # Deleting again still succeeds (idempotent).
    assert mgr.delete_latest_stable_log()


def test_create_latest_stable_rejects_transient(tmp_path):
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    mgr.write_log(0, make_entry(state="CREATING"))
    assert not mgr.create_latest_stable_log(0)
    assert not mgr.create_latest_stable_log(99)


def test_get_log_raises_on_corrupt_entry(tmp_path):
    import pytest
    from hyperspace_tpu.exceptions import HyperspaceException
    mgr = IndexLogManagerImpl(str(tmp_path / "idx"))
    os.makedirs(mgr.log_dir)
    with open(os.path.join(mgr.log_dir, "0"), "w") as f:
        f.write("{not json")
    with pytest.raises(HyperspaceException):
        mgr.get_log(0)


def test_occ_single_winner_across_processes(tmp_path):
    """Optimistic concurrency across real PROCESSES: N workers race to
    write the same log id; exactly one wins (reference
    `IndexLogManager.scala:139-156` — atomic-rename semantics)."""
    import subprocess
    import sys

    script = r"""
import sys
sys.path.insert(0, sys.argv[3])
from hyperspace_tpu.index.log_manager import IndexLogManagerImpl
sys.path.insert(0, sys.argv[4])
from fakes import make_entry
import time
mgr = IndexLogManagerImpl(sys.argv[1])
# Barrier-ish start: spin until the go-file appears, then race.
import os
while not os.path.exists(sys.argv[2]):
    time.sleep(0.001)
print(int(mgr.write_log(5, make_entry(state="CREATING"))))
"""
    import os
    idx = str(tmp_path / "idx")
    go = str(tmp_path / "go")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests_dir = os.path.join(repo, "tests")
    procs = [subprocess.Popen(
        [sys.executable, "-c", script, idx, go, repo, tests_dir],
        stdout=subprocess.PIPE, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        for _ in range(6)]
    (tmp_path / "go").write_text("1")
    outs = [int(p.communicate(timeout=120)[0].strip()) for p in procs]
    assert sum(outs) == 1, outs
